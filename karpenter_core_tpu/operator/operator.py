"""The operator runtime: composition root for the framework.

Mirror of /root/reference/pkg/operator/operator.go:70-177 and
controllers.go:46-73: builds clients, cluster state, informers, and all
controllers, then runs them as singleton loops / watch controllers.  The
consuming binary composes ``Operator(...).with_controllers().start()`` exactly
as cloud-provider repos compose the reference.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.apis.objects import Node, Pod
from karpenter_core_tpu.apis.v1alpha5 import Provisioner
from karpenter_core_tpu.cloudprovider import CloudProvider
from karpenter_core_tpu.controllers.counter import CounterController
from karpenter_core_tpu.controllers.deprovisioning import DeprovisioningController
from karpenter_core_tpu.controllers.inflightchecks import InflightChecksController
from karpenter_core_tpu.controllers.metrics_scrapers import (
    NodeScraper,
    PodScraper,
    ProvisionerScraper,
)
from karpenter_core_tpu.controllers.node import NodeController
from karpenter_core_tpu.controllers.provisioning import PodController, ProvisioningController
from karpenter_core_tpu.controllers.termination import TerminationController
from karpenter_core_tpu.events import Recorder
from karpenter_core_tpu.operator.controller import Singleton, TypedWatchController
from karpenter_core_tpu.operator.kubeclient import KubeClient
from karpenter_core_tpu.operator.options import Options
from karpenter_core_tpu.operator.settings import Settings
from karpenter_core_tpu.state.cluster import Cluster
from karpenter_core_tpu.state.informer import start_informers
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

from karpenter_core_tpu.metrics import REGISTRY  # noqa: E402

LEADER_GAUGE = REGISTRY.gauge(
    "karpenter_leader_election_leader",
    "1 when this replica holds the leadership lease and runs controllers",
)


@dataclass
class Operator:
    cloud_provider: CloudProvider
    options: Options = field(default_factory=Options)
    settings: Settings = field(default_factory=Settings)
    clock: Clock = field(default_factory=Clock)
    kube_client: Optional[KubeClient] = None
    recorder: Optional[Recorder] = None
    # TPU-first by default: large batches route through the device kernel
    # (host oracle handles small/exotic shapes, and the provisioning
    # controller self-disables the device path after repeated backend
    # failures — see TPU_KERNEL_MAX_FAILURES), so the library facade matches
    # the binary (cmd/operator.py KC_TPU_KERNEL default)
    use_tpu_kernel: bool = True
    # serve /metrics (+ /debug/pprof with --enable-profiling) and health
    # probes over HTTP; off by default so embedded/test operators don't bind
    serve_http: bool = False

    def __post_init__(self) -> None:
        if self.kube_client is None:
            # backend selector (--kube-backend=memory|apiserver): the
            # apiserver client's reflectors warm-start cluster state from a
            # LIST, so a restarted operator rebuilds instead of starting blind
            from karpenter_core_tpu.kubeapi import make_kube_client

            self.kube_client = make_kube_client(self.options, clock=self.clock)
        if self.recorder is None:
            self.recorder = Recorder(clock=self.clock.now)
        # live settings: controllers read through the store so ConfigMap
        # updates apply without rewiring (settingsstore.go:94-98)
        from karpenter_core_tpu.operator.settingsstore import SettingsStore

        self.settings_store = SettingsStore(self.kube_client, defaults=self.settings)
        self.settings = self.settings_store
        self.cluster = Cluster(self.clock, self.kube_client, self.cloud_provider, self.settings)
        self._singletons: List[Singleton] = []
        self._watchers: List[TypedWatchController] = []
        self._started = False
        self.leader_elector = None
        self.http = None

    def with_controllers(self) -> "Operator":
        """Wire the full controller set (controllers.go:46-73)."""
        kube, cluster, provider = self.kube_client, self.cluster, self.cloud_provider
        self.provisioning = ProvisioningController(
            kube, provider, cluster,
            recorder=self.recorder, settings=self.settings, clock=self.clock,
            use_tpu_kernel=self.use_tpu_kernel,
        )
        self.deprovisioning = DeprovisioningController(
            self.clock, kube, self.provisioning, provider, self.recorder, cluster,
            self.settings, use_tpu_kernel=self.use_tpu_kernel,
        )
        self.node_lifecycle = NodeController(self.clock, kube, provider, cluster, self.settings)
        self.termination = TerminationController(self.clock, kube, provider, self.recorder)
        self.inflight_checks = InflightChecksController(self.clock, kube, provider, self.recorder)
        self.counter = CounterController(kube, cluster)
        self.node_scraper = NodeScraper(cluster)
        self.pod_scraper = PodScraper(kube)
        self.provisioner_scraper = ProvisionerScraper(kube)

        self._watchers = [
            TypedWatchController(
                "node", Node, kube,
                reconcile=self.node_lifecycle.reconcile,
                finalize=self.termination.reconcile,
            ),
            TypedWatchController(
                "provisioning_trigger", Pod, kube,
                reconcile=PodController(self.provisioning).reconcile,
            ),
            TypedWatchController("counter", Provisioner, kube, reconcile=self.counter.reconcile),
        ]
        self._singletons = [
            Singleton("provisioning", lambda: self._provision(), clock=self.clock, default_requeue=0.1),
            Singleton(
                "deprovisioning",
                lambda: self.deprovisioning.reconcile()[1],
                clock=self.clock,
                default_requeue=self.options.poll_interval,
            ),
            Singleton("metrics_state", self.node_scraper.scrape, clock=self.clock, default_requeue=5.0),
            Singleton(
                "inflightchecks",
                lambda: (self.inflight_checks.reconcile_all(), 60.0)[1],
                clock=self.clock,
                default_requeue=60.0,
            ),
        ]
        return self

    def with_webhooks(self) -> "Operator":
        """Install defaulting/validation admission (webhooks.go:32-69,
        operator.go:157)."""
        from karpenter_core_tpu.operator.webhooks import Webhooks

        self.webhooks = Webhooks(service_name=self.options.service_name)
        self.webhooks.install(self.kube_client)
        return self

    def _provision(self) -> float:
        self.provisioning.reconcile(wait_for_batch=True)
        return 0.1

    def start(self) -> "Operator":
        """Start informers, serving, and — once this replica holds the
        leadership lease (operator.go:111-126) — the controllers.  Informers
        and serving run on every replica; controllers only on the leader."""
        from karpenter_core_tpu.utils import compilecache

        compilecache.enable()  # restarts reuse compiled solve kernels
        if self.options.memory_limit > 0:
            from karpenter_core_tpu.utils import memlimit

            memlimit.apply(self.options.memory_limit)
        self.settings_store.start()
        from karpenter_core_tpu.operator.settingsstore import LoggingConfigWatcher

        self.logging_watcher = LoggingConfigWatcher(self.kube_client).start()
        start_informers(self.cluster, self.kube_client)
        if self.serve_http:
            from karpenter_core_tpu.operator.httpserver import OperatorHTTP

            self.http = OperatorHTTP(
                metrics_port=self.options.metrics_port,
                health_port=self.options.health_probe_port,
                enable_profiling=self.options.enable_profiling,
                healthy=self.healthy,
                ready=self.ready,
            ).start()
        self._started = True
        # export the gauge from boot: a standby that never led must still
        # report 0 (dashboards and the HA failover test poll it)
        LEADER_GAUGE.labels().set(0.0)
        if self.options.enable_leader_election:
            import os

            from karpenter_core_tpu.operator.leaderelection import LeaderElector

            # cross-replica election needs a SHARED lease store: the solver
            # service hosts the lease plane (deploy/manifests — the solver is
            # the deployment's singleton), the in-process store only elects
            # within one process (tests / replicas:1)
            lease_store = None
            endpoint = os.environ.get(
                "KC_LEASE_ENDPOINT", os.environ.get("KC_SOLVER_ADDRESS", "")
            )
            if endpoint:
                from karpenter_core_tpu.service.snapshot_channel import (
                    RemoteLeaseStore,
                )

                lease_store = RemoteLeaseStore(endpoint)
                log.info("leader election through shared lease plane at %s", endpoint)
            self.leader_elector = LeaderElector(
                self.kube_client,
                lease_store=lease_store,
                clock=self.clock,
                on_started_leading=self._start_controllers,
                on_stopped_leading=self._stop_controllers,
            ).start()
        else:
            self._start_controllers()
        return self

    def _start_controllers(self) -> None:
        LEADER_GAUGE.labels().set(1.0)
        for watcher in self._watchers:
            watcher.start()
        for singleton in self._singletons:
            singleton.start()
        log.info(
            "operator running %d controllers",
            len(self._singletons) + len(self._watchers),
        )

    def _stop_controllers(self) -> None:
        LEADER_GAUGE.labels().set(0.0)
        for singleton in self._singletons:
            singleton.stop()
        for watcher in self._watchers:
            watcher.stop()

    def stop(self) -> None:
        if self.leader_elector is not None:
            self.leader_elector.stop()  # releases the lease for standbys
        self._stop_controllers()
        # let an in-flight speculative compile finish: tearing the process
        # down mid-compile aborts in native code.  Bounded WELL below the
        # manifest's terminationGracePeriodSeconds (30 s) so the rest of
        # shutdown always runs before the kubelet's SIGKILL.
        if getattr(self, "provisioning", None) is not None:
            self.provisioning.join_warmup(timeout=15.0)
        if self.http is not None:
            self.http.stop()
        # apiserver backend: tear down reflector threads / watch streams
        close = getattr(self.kube_client, "close", None)
        if close is not None:
            close()
        self._started = False

    def healthy(self) -> bool:
        """Liveness: the replica is up (leaders and standbys alike)."""
        return self._started

    def ready(self) -> bool:
        """Readiness: the replica can serve (standbys included — gating
        readiness on leadership would zero the PDB budget and pull standbys
        out of Services; leadership is observable via is_leader() and the
        karpenter_leader_election_leader gauge instead)."""
        return self._started

    def is_leader(self) -> bool:
        return self.leader_elector is None or self.leader_elector.is_leader
