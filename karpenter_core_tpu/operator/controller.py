"""Controller framework: singleton loops, typed watch controllers, metrics.

Mirror of /root/reference/pkg/operator/controller/{controller.go:25-45,
singleton.go:92-122, typed.go:33-84}: a Singleton runs a self-ticking reconcile
loop with per-controller duration metrics and rate-limited requeue; a typed
watch controller dispatches object events (routing deleting objects to
Finalize, typed.go:75-78).
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
from typing import Callable, Optional

from karpenter_core_tpu.metrics import REGISTRY, measure
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

RECONCILE_DURATION = REGISTRY.histogram(
    "controller_runtime_reconcile_time_seconds",
    "Length of time per reconciliation per controller",
    ("controller",),
)
RECONCILE_ERRORS = REGISTRY.counter(
    "controller_runtime_reconcile_errors_total",
    "Total number of reconciliation errors per controller",
    ("controller",),
)


class Singleton:
    """Self-ticking reconcile loop (singleton.go:92-122).  ``reconcile``
    returns the requeue-after in seconds (None = default)."""

    def __init__(
        self,
        name: str,
        reconcile: Callable[[], Optional[float]],
        clock: Optional[Clock] = None,
        default_requeue: float = 10.0,
    ) -> None:
        self.name = name
        self.reconcile = reconcile
        self.clock = clock or Clock()
        self.default_requeue = default_requeue
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # fresh stop-event per start (see TypedWatchController.start)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop,), name=self.name, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            requeue = self.tick()
            stop.wait(timeout=requeue)

    def tick(self) -> float:
        done = measure(RECONCILE_DURATION.labels(self.name))
        try:
            requeue = self.reconcile()
        except Exception:  # noqa: BLE001 - controller loops never die
            log.exception("reconciling %s", self.name)
            RECONCILE_ERRORS.labels(self.name).inc()
            requeue = None
        finally:
            done()
        return requeue if requeue is not None else self.default_requeue


class TypedWatchController:
    """Watch-driven controller for one object kind (typed.go:33-84): routes
    deleting objects to ``finalize`` and live ones to ``reconcile``.

    Events flow through a deduping workqueue drained by a worker thread —
    controller-runtime semantics.  Without the queue, a reconcile that mutates
    its own object (e.g. termination cordoning a node) re-enters itself through
    the synchronous watch dispatch and recurses.
    """

    def __init__(
        self,
        name: str,
        kind: type,
        kube_client,
        reconcile: Callable,
        finalize: Optional[Callable] = None,
    ) -> None:
        self.name = name
        self.kind = kind
        self.kube_client = kube_client
        self.reconcile = reconcile
        self.finalize = finalize
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        self._pending = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._timers: set = set()

    def start(self) -> None:
        # fresh stop-event and queue per start: a previous worker that
        # outlived its stop() join (long reconcile) keeps its own, already-set
        # event and drained queue, so it can neither revive nor steal work
        self._stop = threading.Event()
        self._queue = queue_mod.Queue()
        with self._lock:
            self._pending.clear()
        self._thread = threading.Thread(
            target=self._worker, args=(self._stop, self._queue),
            name=self.name, daemon=True,
        )
        self._thread.start()
        if not getattr(self, "_watching", False):
            self.kube_client.watch(self.kind, self._on_event)
            self._watching = True
        else:
            # re-acquired leadership: resync everything missed while standby
            for obj in self.kube_client.list(self.kind):
                self._on_event("MODIFIED", obj)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            for timer in self._timers:
                timer.cancel()
            self._timers.clear()
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _on_event(self, event_type: str, obj) -> None:
        if self._stop.is_set():
            return  # standby (lost leadership): don't accumulate a backlog
        if event_type == "DELETED":
            return
        key = (obj.metadata.namespace, obj.metadata.name)
        with self._lock:
            if key in self._pending:
                return  # dedupe: already queued
            self._pending.add(key)
        self._queue.put((key, obj))

    def _worker(self, stop: threading.Event, queue: "queue_mod.Queue") -> None:
        while not stop.is_set():
            item = queue.get()
            if item is None:
                return
            key, obj = item
            with self._lock:
                self._pending.discard(key)
            done = measure(RECONCILE_DURATION.labels(self.name))
            try:
                # re-fetch: the queued object may be stale (the namespace arg
                # is ignored for cluster-scoped kinds)
                stored = self.kube_client.get(self.kind, key[1], key[0])
                if stored is None:
                    continue
                if stored.metadata.deletion_timestamp is not None and self.finalize is not None:
                    requeue = self.finalize(stored)
                else:
                    requeue = self.reconcile(stored)
                if requeue is not None and not stop.is_set():
                    # schedule a delayed requeue without blocking the worker;
                    # honor the controller's interval (drift polls at 5 min)
                    timer = threading.Timer(
                        float(requeue), self._requeue_cb(stored)
                    )
                    timer.daemon = True
                    with self._lock:
                        self._timers = {t for t in self._timers if t.is_alive()}
                        self._timers.add(timer)
                    timer.start()
            except Exception:  # noqa: BLE001
                log.exception("reconciling %s", self.name)
                RECONCILE_ERRORS.labels(self.name).inc()
            finally:
                done()

    def _requeue_cb(self, obj):
        def fire():
            if not self._stop.is_set():
                self._on_event("MODIFIED", obj)

        return fire
