"""Admission webhooks: defaulting + validation on writes.

Mirror of /root/reference/pkg/webhooks/webhooks.go:32-69: the reference runs
knative defaulting/validation admission controllers as a second process; here
admission hooks intercept KubeClient writes for the registered kinds, applying
SetDefaults then Validate and rejecting invalid objects.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from karpenter_core_tpu.apis import validation as validation_api
from karpenter_core_tpu.apis.v1alpha5 import Provisioner


class AdmissionError(Exception):
    def __init__(self, errors: List[str]) -> None:
        super().__init__("; ".join(errors))
        self.errors = errors


class Webhooks:
    """Wraps a KubeClient's create/update/apply with admission chains.

    ``service_name`` identifies the serving endpoint admission requests are
    attributed to (--karpenter-service, the reference's webhook Service name,
    options.go:58) — informational for the in-process admission path."""

    def __init__(self, service_name: str = "") -> None:
        self.service_name = service_name
        self.defaulters: Dict[type, Callable] = {Provisioner: validation_api.set_defaults}
        self.validators: Dict[type, Callable] = {Provisioner: validation_api.validate_provisioner}

    def admit(self, obj):
        defaulter = self.defaulters.get(type(obj))
        if defaulter is not None:
            obj = defaulter(obj)
        validator = self.validators.get(type(obj))
        if validator is not None:
            errors = validator(obj)
            if errors:
                raise AdmissionError(errors)
        return obj

    def install(self, kube_client) -> None:
        """Decorate the client's mutating entry points."""
        original_create, original_update = kube_client.create, kube_client.update

        def create(obj):
            return original_create(self.admit(obj))

        def update(obj):
            return original_update(self.admit(obj))

        kube_client.create = create
        kube_client.update = update
