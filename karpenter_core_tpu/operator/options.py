"""Static options (mirror of /root/reference/pkg/operator/options/options.go:34-87):
flag/env configuration for the operator process."""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class Options:
    service_name: str = ""
    metrics_port: int = 8080  # options.go:59
    health_probe_port: int = 8081
    kube_client_qps: int = 200  # options.go:61
    kube_client_burst: int = 300  # options.go:62
    enable_profiling: bool = False
    enable_leader_election: bool = True
    memory_limit: int = -1  # bytes; GC soft limit at 90% (options.go:67-70)
    poll_interval: float = 10.0
    # watch/list plane backend: "memory" (hermetic in-process store, the test
    # default) or "apiserver" (real list/watch protocol via kubeapi/ against
    # kube_apiserver; closes the §5.4 restart-rebuild gap — docs/KUBEAPI.md)
    kube_backend: str = "memory"
    kube_apiserver: str = ""  # http endpoint, e.g. http://127.0.0.1:8001

    @classmethod
    def parse(cls, argv: Optional[List[str]] = None) -> "Options":
        parser = argparse.ArgumentParser("karpenter-core-tpu")
        parser.add_argument("--karpenter-service", default=_env("KARPENTER_SERVICE", ""))
        parser.add_argument("--metrics-port", type=int, default=int(_env("METRICS_PORT", "8080")))
        parser.add_argument(
            "--health-probe-port", type=int, default=int(_env("HEALTH_PROBE_PORT", "8081"))
        )
        parser.add_argument(
            "--kube-client-qps", type=int, default=int(_env("KUBE_CLIENT_QPS", "200"))
        )
        parser.add_argument(
            "--kube-client-burst", type=int, default=int(_env("KUBE_CLIENT_BURST", "300"))
        )
        parser.add_argument(
            "--enable-profiling", action="store_true", default=_env_bool("ENABLE_PROFILING", False)
        )
        parser.add_argument(
            "--leader-elect",
            action=argparse.BooleanOptionalAction,
            default=_env_bool("LEADER_ELECT", True),
        )
        parser.add_argument(
            "--memory-limit", type=int, default=int(_env("MEMORY_LIMIT", "-1"))
        )
        parser.add_argument(
            "--kube-backend",
            choices=("memory", "apiserver"),
            default=_env("KC_KUBE_BACKEND", "memory"),
        )
        parser.add_argument(
            "--kube-apiserver", default=_env("KC_KUBE_APISERVER", "")
        )
        # argv=None means the process command line (standard argparse contract);
        # pass [] explicitly for defaults-only parsing
        args = parser.parse_args(argv)
        return cls(
            service_name=args.karpenter_service,
            metrics_port=args.metrics_port,
            health_probe_port=args.health_probe_port,
            kube_client_qps=args.kube_client_qps,
            kube_client_burst=args.kube_client_burst,
            enable_profiling=args.enable_profiling,
            enable_leader_election=args.leader_elect,
            memory_limit=args.memory_limit,
            kube_backend=args.kube_backend,
            kube_apiserver=args.kube_apiserver,
        )


def _env(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() in ("1", "true", "yes")
