"""Leader election over a coordination Lease.

Mirror of the reference's leader-elected replicas
(/root/reference/pkg/operator/operator.go:111-126, options.go:64 — client-go
leaderelection with a Lease lock): one replica holds the lease and runs the
controllers; standbys retry acquisition every ``retry_period`` and take over
when the lease stops changing for ``lease_duration`` of the STANDBY'S clock
time (client-go's observedTime discipline — never a comparison against the
renewTime the holder's clock wrote, which would make the safety margin
clock-skew-sensitive).  Acquisition is a CAS on the lease's resourceVersion
(KubeClient.update_with_version), so two racing electors can never both win
a term.

The reference process exits when it loses leadership (client-go's default
OnStoppedLeading is a fatal); the in-process equivalent is the
``on_stopped_leading`` callback, which the Operator wires to stop its
controllers.
"""

from __future__ import annotations

import copy
import logging
import os
import socket
import threading
import uuid
from typing import Callable, Optional

from karpenter_core_tpu.apis.objects import Lease, LeaseSpec, ObjectMeta
from karpenter_core_tpu.operator.kubeclient import ConflictError
from karpenter_core_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

LEASE_NAME = "karpenter-leader-election"
# elect in the namespace the operator runs in (the deployment injects
# SYSTEM_NAMESPACE from metadata.namespace; RBAC grants lease write there)
LEASE_NAMESPACE = os.environ.get("SYSTEM_NAMESPACE", "kube-system")


def default_identity() -> str:
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    def __init__(
        self,
        kube_client,
        lease_store=None,
        clock: Optional[Clock] = None,
        identity: Optional[str] = None,
        lease_name: str = LEASE_NAME,
        namespace: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_deadline: Optional[float] = None,
        retry_period: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        self.kube_client = kube_client
        # where the Lease object lives: the process-local KubeClient (single
        # replica / tests) or a RemoteLeaseStore against the shared solver
        # service, which is what makes CROSS-process election real — each
        # replica's in-memory store can only ever elect itself
        self.lease_store = lease_store if lease_store is not None else kube_client
        self.clock = clock or Clock()
        self.identity = identity or default_identity()
        self.lease_name = lease_name
        self.namespace = namespace or os.environ.get("SYSTEM_NAMESPACE", "kube-system")
        self.lease_duration = lease_duration
        # client-go RenewDeadline analog: a leader that hasn't SUCCESSFULLY
        # renewed within this window self-demotes — without it, a leader
        # partitioned from a remote lease store would keep running controllers
        # while a standby (who can still reach the store) promotes: split-brain
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None else lease_duration * 2 / 3
        )
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = False
        self._last_renew = 0.0  # clock time of the last successful acquire/renew
        # client-go style observation tracking: staleness is judged against
        # the LOCAL clock time at which this elector last saw the lease
        # change, never against the renewTime the holder's clock wrote —
        # otherwise ~renew-margin seconds of clock skew between replicas lets
        # a standby promote while the old leader still acts (ADVICE r4 #1)
        self._observed_key: Optional[tuple] = None
        self._observed_at = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(
            target=self._run, name="leader-election", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop electing; release the lease if held so a standby takes over
        immediately (leaderelection.release semantics)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.is_leader:
            # stop this replica's controllers BEFORE handing the lease over —
            # releasing first would let a standby act while our in-flight
            # reconciles drain (dual-leader window on every rollout)
            self._demote()
            self._release()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the elector loop never dies
                log.exception("leader election tick")
                self._check_renew_deadline()
            self._stop.wait(timeout=self.retry_period)

    def _check_renew_deadline(self) -> None:
        """Self-demote when renewal hasn't landed within the deadline (the
        lease store may be unreachable; a standby may already have promoted —
        stop acting BEFORE the staleness window hands leadership over)."""
        if self.is_leader and self.clock.now() - self._last_renew > self.renew_deadline:
            log.warning(
                "leader election: %s renew deadline (%.0fs) exceeded, demoting",
                self.identity, self.renew_deadline,
            )
            self._demote()

    # -- protocol --------------------------------------------------------------

    def tick(self) -> bool:
        """One acquire/renew attempt; returns is_leader.  Callable directly in
        tests for deterministic stepping."""
        now = self.clock.now()
        stored = self.lease_store.get(Lease, self.lease_name, self.namespace)
        # the in-memory client hands out live references: mutate a COPY and
        # CAS with the version snapshotted at read time, or two electors
        # racing through the same object would both "win"
        lease = copy.deepcopy(stored)
        seen_version = stored.metadata.resource_version if stored is not None else None
        if lease is None:
            created = Lease(
                metadata=ObjectMeta(name=self.lease_name, namespace=self.namespace),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    lease_duration_seconds=int(self.lease_duration),
                    acquire_time=now,
                    renew_time=now,
                ),
            )
            try:
                self.lease_store.create(created)
            except ConflictError:
                # lost the create race; if we were leading, the lease vanished
                # under us (store restart) and someone else now holds it
                self._demote()
                return self._deadline_checked()
            self._last_renew = now
            self._promote()
            return True

        if lease.spec.holder_identity == self.identity:
            lease.spec.renew_time = now
            try:
                self.lease_store.update_with_version(lease, seen_version)
            except ConflictError:
                # only another writer can bump the version under our identity:
                # a takeover or a store reset — either way we no longer hold it
                self._demote()
                return self._deadline_checked()
            self._last_renew = now
            self._promote()
            return True

        # stale-holder takeover: the holder is deemed dead when the lease has
        # not CHANGED for lease_duration of OUR clock time (each change —
        # holder, version, renewTime — restamps the local observation time).
        # A released lease (empty holder) is free immediately.
        obs_key = (lease.spec.holder_identity, seen_version, lease.spec.renew_time)
        if obs_key != self._observed_key:
            self._observed_key = obs_key
            self._observed_at = now
        holder_stale = (
            not lease.spec.holder_identity
            or now - self._observed_at > self.lease_duration
        )
        if holder_stale:
            lease.spec.holder_identity = self.identity
            lease.spec.acquire_time = now
            lease.spec.renew_time = now
            lease.spec.lease_transitions += 1
            try:
                self.lease_store.update_with_version(lease, seen_version)
            except ConflictError:
                return self._deadline_checked()  # another standby won the takeover
            log.info(
                "leader election: %s took over (transition %d)",
                self.identity, lease.spec.lease_transitions,
            )
            self._last_renew = now
            self._promote()
            return True

        # someone else holds a fresh lease
        self._demote()
        return False

    def _deadline_checked(self) -> bool:
        self._check_renew_deadline()
        return self.is_leader

    def _release(self) -> None:
        try:
            stored = self.lease_store.get(Lease, self.lease_name, self.namespace)
            if stored is not None and stored.spec.holder_identity == self.identity:
                lease = copy.deepcopy(stored)
                lease.spec.holder_identity = ""
                lease.spec.renew_time = 0.0
                self.lease_store.update_with_version(
                    lease, stored.metadata.resource_version
                )
        except ConflictError:
            pass
        except Exception as e:  # noqa: BLE001 - a failed release must not
            # abort shutdown; the standby waits out lease staleness instead
            log.warning("leader election: lease release failed (%s)", e)

    def _promote(self) -> None:
        if not self.is_leader:
            self.is_leader = True
            log.info("leader election: %s acquired leadership", self.identity)
            if self.on_started_leading is not None:
                self.on_started_leading()

    def _demote(self) -> None:
        if self.is_leader:
            self.is_leader = False
            log.warning("leader election: %s lost leadership", self.identity)
            if self.on_stopped_leading is not None:
                self.on_stopped_leading()
