"""Operator HTTP endpoints: metrics exposition, health probes, profiling.

Mirror of the reference's serving surface:

  - /metrics on the metrics port (controller-runtime metrics server;
    options.go:59) — Prometheus text from metrics.REGISTRY.render()
  - /healthz and /readyz on the health-probe port (operator.go:100-108)
  - /debug/pprof/* on the metrics port when --enable-profiling is set
    (/root/reference/pkg/operator/profiling.go:25-40).  Python has no pprof,
    so the equivalents are:
      /debug/pprof/profile?seconds=N  stack-sampling CPU profile over all
                                      threads, collapsed-stack text output
                                      (flamegraph-compatible)
      /debug/pprof/heap               tracemalloc top allocations (started on
                                      first request)
      /debug/pprof/device             accelerator memory stats (jax)
  - /debug/traces on the metrics port — the last N completed solve traces
    (tracing.TRACE_STORE) with their decision audits; ``?n=K`` limits,
    ``?format=chrome`` emits Chrome trace-event JSON for chrome://tracing /
    Perfetto.  See docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import collections
import json
import logging
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY

log = logging.getLogger(__name__)


def sample_stacks(seconds: float = 1.0, interval: float = 0.005) -> str:
    """Collapsed-stack CPU profile: sample every thread's Python stack at
    ``interval`` for ``seconds``; one `frame;frame;frame count` line per
    distinct stack (the folded format flamegraph.pl / speedscope read)."""
    counts: collections.Counter = collections.Counter()
    own = threading.get_ident()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for thread_id, frame in sys._current_frames().items():
            if thread_id == own:
                continue
            stack = []
            while frame is not None:
                code = frame.f_code
                stack.append(f"{code.co_name} ({code.co_filename}:{frame.f_lineno})")
                frame = frame.f_back
            counts[";".join(reversed(stack))] += 1
        time.sleep(interval)
    return "\n".join(f"{stack} {count}" for stack, count in counts.most_common())


def heap_profile(limit: int = 50) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc started; request again for a snapshot\n"
    snapshot = tracemalloc.take_snapshot()
    lines = [str(stat) for stat in snapshot.statistics("lineno")[:limit]]
    current, peak = tracemalloc.get_traced_memory()
    lines.append(f"traced: current={current} peak={peak}")
    return "\n".join(lines)


def device_profile() -> str:
    try:
        import jax

        lines = []
        for device in jax.local_devices():
            stats = device.memory_stats() or {}
            lines.append(f"{device}:")
            for key, value in sorted(stats.items()):
                lines.append(f"  {key}: {value}")
        return "\n".join(lines) or "no devices"
    except Exception as e:  # noqa: BLE001 - profiling must not crash the operator
        return f"device stats unavailable: {e}"


class OperatorHTTP:
    """Two listeners, matching the reference's port split: metrics (+pprof
    when enabled) on ``metrics_port``, health probes on ``health_port``."""

    def __init__(
        self,
        metrics_port: int = 8080,
        health_port: int = 8081,
        enable_profiling: bool = False,
        healthy: Optional[Callable[[], bool]] = None,
        ready: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.enable_profiling = enable_profiling
        self.healthy = healthy or (lambda: True)
        self.ready = ready or (lambda: True)
        outer = self

        class MetricsHandler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # noqa: A003 - quiet
                pass

            def do_GET(self) -> None:  # noqa: N802 - http.server contract
                parsed = urlparse(self.path)
                if parsed.path == "/metrics":
                    query = parse_qs(parsed.query)
                    if query.get("exemplars", ["0"])[0] == "1":
                        # exemplar suffixes use OpenMetrics *syntax* but this
                        # registry's families are not strict-OpenMetrics
                        # conformant (counter _total suffix rules), so the
                        # content type stays text/plain: ?exemplars=1 is the
                        # human/debug view for trace correlation — point
                        # scrapers at the default /metrics
                        return self._text(200, REGISTRY.render(exemplars=True))
                    return self._text(200, REGISTRY.render())
                if parsed.path == "/debug/traces":
                    # same posture as /debug/pprof: debug data (pod names,
                    # failure strings) is not exposed on a default deployment —
                    # but enabling tracing (KC_TRACE=1 / tracing.enable()) IS
                    # the opt-in, so either flag unlocks the endpoint
                    if not (outer.enable_profiling or tracing.enabled()):
                        return self._text(
                            403, "tracing disabled (KC_TRACE=1 or --enable-profiling)\n"
                        )
                    return self._traces(parse_qs(parsed.query))
                if parsed.path.startswith("/debug/pprof"):
                    if not outer.enable_profiling:
                        return self._text(403, "profiling disabled (--enable-profiling)\n")
                    if parsed.path == "/debug/pprof/profile":
                        raw = parse_qs(parsed.query).get("seconds", ["1"])[0]
                        try:
                            seconds = float(raw)
                        except ValueError:
                            return self._text(400, f"bad seconds: {raw!r}\n")
                        if not (0 < seconds <= 60.0):
                            seconds = min(max(seconds, 0.1), 60.0) if seconds == seconds else 1.0
                        return self._text(200, sample_stacks(seconds))
                    if parsed.path == "/debug/pprof/heap":
                        return self._text(200, heap_profile())
                    if parsed.path == "/debug/pprof/device":
                        return self._text(200, device_profile())
                return self._text(404, "not found\n")

            def _traces(self, query) -> None:
                """The last N solve traces as JSON; ``format=chrome`` emits
                trace-event JSON loadable in chrome://tracing / Perfetto.
                ``?trace_id=<id>`` returns the MERGED tree for that trace:
                every stored segment sharing the id (client solve, server
                session tick, coalesced dispatch, journal replay) stitched
                into one span list (tracing.TraceStore.tree)."""
                try:
                    n = int(query.get("n", ["0"])[0])
                except ValueError:
                    return self._text(400, f"bad n: {query.get('n')!r}\n")
                trace_id = query.get("trace_id", [""])[0]
                if trace_id:
                    tree = tracing.TRACE_STORE.tree(trace_id)
                    if tree is None:
                        return self._text(404, f"no trace {trace_id!r}\n")
                    if query.get("format", [""])[0] == "chrome":
                        return self._json(200, tracing.to_chrome([tree]))
                    return self._json(
                        200,
                        {
                            "enabled": tracing.enabled(),
                            "trace": tree.to_dict(),
                            "audits": list(tree.audits()),
                        },
                    )
                traces = tracing.TRACE_STORE.last(n if n > 0 else None)
                if query.get("format", [""])[0] == "chrome":
                    return self._json(200, tracing.to_chrome(traces))
                return self._json(
                    200,
                    {
                        "enabled": tracing.enabled(),
                        "capacity": tracing.TRACE_STORE.capacity,
                        "traces": [t.to_dict() for t in traces],
                        "audits": [
                            {"traceId": t.trace_id, **audit}
                            for t in traces
                            for audit in t.audits()
                        ],
                    },
                )

            def _json(self, status: int, payload) -> None:
                data = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _text(self, status: int, body: str) -> None:
                data = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        class HealthHandler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # noqa: A003 - quiet
                pass

            def do_GET(self) -> None:  # noqa: N802 - http.server contract
                if self.path.startswith("/healthz"):
                    ok = outer.healthy()
                elif self.path.startswith("/readyz"):
                    ok = outer.ready()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = b"ok\n" if ok else b"unhealthy\n"
                self.send_response(200 if ok else 503)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._metrics_server = ThreadingHTTPServer(("0.0.0.0", metrics_port), MetricsHandler)
        self._health_server = ThreadingHTTPServer(("0.0.0.0", health_port), HealthHandler)
        self.metrics_port = self._metrics_server.server_address[1]
        self.health_port = self._health_server.server_address[1]

    def start(self) -> "OperatorHTTP":
        for server in (self._metrics_server, self._health_server):
            threading.Thread(target=server.serve_forever, daemon=True).start()
        log.info(
            "serving /metrics + /debug/traces%s on :%d, probes on :%d",
            " + /debug/pprof" if self.enable_profiling else "",
            self.metrics_port, self.health_port,
        )
        return self

    def stop(self) -> None:
        self._metrics_server.shutdown()
        self._health_server.shutdown()
