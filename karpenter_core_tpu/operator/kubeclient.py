"""In-memory object store standing where the kube-apiserver stands.

The reference's distributed communication backend is the apiserver watch/list
plane (SURVEY.md §5.8; controller-runtime informers).  This framework is
standalone: the KubeClient is the single source of truth for API objects, with
list/get/create/update/delete plus watch callbacks that pump the state cluster
informers (karpenter_core_tpu.state.informer).  Thread-safe; watch events are
delivered synchronously in the mutating thread.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from karpenter_core_tpu.apis.objects import (
    CSINode,
    Lease,
    LabelSelector,
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    StorageClass,
    deep_copy,
)
from karpenter_core_tpu.apis.v1alpha5 import Machine, Provisioner
from karpenter_core_tpu.chaos import plane as _chaos

WatchFunc = Callable[[str, object], None]  # (event_type, object); ADDED|MODIFIED|DELETED

# the kubeapi.put injection point covers every client-side mutation (create/
# update/apply/delete) on BOTH kube backends: the in-memory client fires it in
# _throttle(), the apiserver transport (kubeapi/client.py) imports this Point
# and fires it per mutating HTTP request — one name, one registration.
KUBEAPI_PUT = _chaos.point("kubeapi.put")


class ConflictError(Exception):
    pass


class NotFoundError(Exception):
    pass


def raise_injected_kubeapi_fault(fault: "_chaos.Fault") -> None:
    """Map an injected kubeapi fault onto the client error surface callers
    already handle: 404 → NotFoundError, 409 → ConflictError, anything else
    (incl. timeout kinds) → InjectedFault.  Shared by both backends so a
    chaos scenario behaves identically against either."""
    if fault.code == 404:
        raise NotFoundError(fault.describe())
    if fault.code == 409:
        raise ConflictError(fault.describe())
    raise _chaos.InjectedFault(fault)


class RateLimiter:
    """Client-side mutation throttle (--kube-client-qps/-burst,
    options.go:61-62): token bucket over create/update/delete.  Shared by the
    in-memory KubeClient and the apiserver-backed client (kubeapi.client) so
    both backends meter writes identically.  ``qps`` None/0 disables."""

    def __init__(self, qps: "Optional[float]", burst: "Optional[int]",
                 now=None, sleep=None) -> None:
        import time as _time

        self._now = now or _time.time
        self._sleep = sleep or _time.sleep
        self._qps = qps
        if qps:
            self._burst = max(burst if burst is not None else int(qps * 1.5), 1)
        else:
            self._burst = None
        self._tokens = float(self._burst or 0)
        self._last_refill = self._now()
        self._lock = threading.Lock()

    def take(self) -> None:
        if not self._qps:
            return
        while True:
            with self._lock:
                now = self._now()
                self._tokens = min(
                    float(self._burst), self._tokens + (now - self._last_refill) * self._qps
                )
                self._last_refill = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self._qps
            self._sleep(wait)


class _Store:
    """One kind's storage: keyed by (namespace, name) or name for cluster scope."""

    def __init__(self, namespaced: bool) -> None:
        self.namespaced = namespaced
        self.objects: Dict[tuple, object] = {}
        self.watchers: List[WatchFunc] = []

    def key(self, obj) -> tuple:
        meta = obj.metadata
        return (meta.namespace, meta.name) if self.namespaced else (meta.name,)


class KubeClient:
    def __init__(self, clock=None, qps: "Optional[float]" = None, burst: "Optional[int]" = None) -> None:
        import time as _time

        self._now = clock.now if clock is not None else _time.time
        self._sleep = clock.sleep if clock is not None else _time.sleep
        self._limiter = RateLimiter(qps, burst, now=self._now, sleep=self._sleep)
        self._lock = threading.RLock()
        self._stores: Dict[type, _Store] = {
            Pod: _Store(True),
            Node: _Store(False),
            Provisioner: _Store(False),
            Machine: _Store(False),
            Namespace: _Store(False),
            PodDisruptionBudget: _Store(True),
            PersistentVolumeClaim: _Store(True),
            PersistentVolume: _Store(False),
            StorageClass: _Store(False),
            CSINode: _Store(False),
            Lease: _Store(True),
        }
        self._resource_version = 0

    # -- generic CRUD ---------------------------------------------------------

    def _store(self, kind: type) -> _Store:
        if kind not in self._stores:
            self._stores[kind] = _Store(hasattr(kind, "namespace"))
        return self._stores[kind]

    def _throttle(self) -> None:
        self._limiter.take()
        fault = KUBEAPI_PUT.hit(
            kinds=(_chaos.KIND_ERROR, _chaos.KIND_TIMEOUT), backend="memory"
        )
        if fault is not None and fault.kind in (_chaos.KIND_ERROR, _chaos.KIND_TIMEOUT):
            raise_injected_kubeapi_fault(fault)

    def create(self, obj) -> object:
        self._throttle()
        return self._create(obj)

    def _create(self, obj) -> object:
        with self._lock:
            store = self._store(type(obj))
            key = store.key(obj)
            if key in store.objects:
                raise ConflictError(f"{type(obj).__name__} {key} already exists")
            self._resource_version += 1
            obj.metadata.resource_version = self._resource_version
            if not obj.metadata.creation_timestamp:
                obj.metadata.creation_timestamp = self._now()
            store.objects[key] = obj
            watchers = list(store.watchers)
        for w in watchers:
            w("ADDED", obj)
        return obj

    def get(self, kind: type, name: str, namespace: Optional[str] = None):
        with self._lock:
            store = self._store(kind)
            key = (namespace, name) if store.namespaced else (name,)
            return store.objects.get(key)

    def update(self, obj) -> object:
        self._throttle()
        return self._update(obj)

    def _update(self, obj, expected_version: "Optional[int]" = None) -> object:
        with self._lock:
            store = self._store(type(obj))
            key = store.key(obj)
            stored = store.objects.get(key)
            if stored is None:
                raise NotFoundError(f"{type(obj).__name__} {key} not found")
            if (
                expected_version is not None
                and stored.metadata.resource_version != expected_version
            ):
                raise ConflictError(
                    f"{type(obj).__name__} {key} resourceVersion "
                    f"{stored.metadata.resource_version} != {expected_version}"
                )
            self._resource_version += 1
            obj.metadata.resource_version = self._resource_version
            store.objects[key] = obj
            watchers = list(store.watchers)
        for w in watchers:
            w("MODIFIED", obj)
        return obj

    def update_with_version(self, obj, expected_resource_version: int) -> object:
        """Optimistic-concurrency update: fails with ConflictError when the
        stored object's resourceVersion moved past ``expected`` — the CAS the
        leader-election lease protocol needs (client-go semantics).

        ``obj`` must be the caller's own COPY and ``expected`` the version
        snapshotted at read time: this in-memory client hands out live object
        references, so a CAS against a shared mutated object is vacuous."""
        self._throttle()
        return self._update(obj, expected_version=expected_resource_version)

    def apply(self, obj) -> object:
        """create-or-update.  Watch callbacks must never fire under the store
        lock (informer callbacks take Cluster locks whose holders call back
        into this client — AB-BA), so this composes the unlocked primitives."""
        self._throttle()
        try:
            return self._create(obj)
        except ConflictError:
            return self._update(obj)

    def delete(self, obj, *, force: bool = False) -> None:
        """Sets deletion timestamp; the object is removed once finalizers clear
        (or immediately with no finalizers) — k8s deletion semantics."""
        self._throttle()
        with self._lock:
            store = self._store(type(obj))
            key = store.key(obj)
            stored = store.objects.get(key)
            if stored is None:
                raise NotFoundError(f"{type(obj).__name__} {key} not found")
            if stored.metadata.finalizers and not force:
                if stored.metadata.deletion_timestamp is None:
                    stored.metadata.deletion_timestamp = self._now()
                    self._resource_version += 1
                    stored.metadata.resource_version = self._resource_version
                    watchers = list(store.watchers)
                    event = ("MODIFIED", stored)
                else:
                    return
            else:
                del store.objects[key]
                watchers = list(store.watchers)
                event = ("DELETED", stored)
        for w in watchers:
            w(*event)

    def remove_finalizer(self, obj, finalizer: str) -> None:
        with self._lock:
            store = self._store(type(obj))
            stored = store.objects.get(store.key(obj))
            if stored is None:
                return
            if finalizer in stored.metadata.finalizers:
                stored.metadata.finalizers = [
                    f for f in stored.metadata.finalizers if f != finalizer
                ]
            should_remove = (
                stored.metadata.deletion_timestamp is not None
                and not stored.metadata.finalizers
            )
        self.update(stored)
        if should_remove:
            self.delete(stored, force=True)

    def list(self, kind: type, namespace: Optional[str] = None, selector=None) -> list:
        with self._lock:
            store = self._store(kind)
            out = []
            for key, obj in store.objects.items():
                if namespace is not None and store.namespaced and key[0] != namespace:
                    continue
                if selector is not None and not _selector_matches(selector, obj):
                    continue
                out.append(obj)
            return out

    def watch(self, kind: type, callback: WatchFunc, *, replay: bool = True) -> None:
        with self._lock:
            store = self._store(kind)
            store.watchers.append(callback)
            existing = list(store.objects.values()) if replay else []
        for obj in existing:
            callback("ADDED", obj)

    # -- typed conveniences (shapes used by scheduler/topology/volumes) -------

    def list_pods(self, namespace: Optional[str] = None, selector=None) -> List[Pod]:
        return self.list(Pod, namespace=namespace, selector=selector)

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self.get(Pod, name, namespace)

    def get_node(self, name: str) -> Optional[Node]:
        return self.get(Node, name)

    def list_nodes(self) -> List[Node]:
        return self.list(Node)

    def list_namespaces(self, selector=None) -> List[Namespace]:
        return self.list(Namespace, selector=selector)

    def list_provisioners(self) -> List[Provisioner]:
        return self.list(Provisioner)

    def get_persistent_volume_claim(self, namespace: str, name: str):
        return self.get(PersistentVolumeClaim, name, namespace)

    def get_persistent_volume(self, name: str):
        return self.get(PersistentVolume, name)

    def get_storage_class(self, name: str):
        return self.get(StorageClass, name)

    def get_csi_node(self, name: str):
        return self.get(CSINode, name)

    def deep_copy(self, obj):
        return deep_copy(obj)


def _selector_matches(selector, obj) -> bool:
    if isinstance(selector, LabelSelector):
        return selector.matches(obj.metadata.labels)
    if isinstance(selector, dict):
        return all(obj.metadata.labels.get(k) == v for k, v in selector.items())
    if callable(selector):
        return selector(obj)
    raise TypeError(f"unsupported selector {selector!r}")
