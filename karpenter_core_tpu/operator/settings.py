"""Dynamic settings (mirror of /root/reference/pkg/apis/config/settings/settings.go:33-112).

The reference watches a ``karpenter-global-settings`` ConfigMap; here Settings
is a plain dataclass validated on construction, swappable at runtime through
the SettingsStore (operator.settingsstore).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass
class Settings:
    batch_max_duration: float = 10.0  # seconds (settings.go:39)
    batch_idle_duration: float = 1.0  # seconds (settings.go:40)
    drift_enabled: bool = False  # featureGates.driftEnabled (settings.go:58)

    def __post_init__(self) -> None:
        errs = []
        if self.batch_max_duration <= 0:
            errs.append("batchMaxDuration cannot be negative or zero")
        if self.batch_idle_duration <= 0:
            errs.append("batchIdleDuration cannot be negative or zero")
        if errs:
            raise ValueError("validating settings, " + "; ".join(errs))

    @classmethod
    def from_config_map(cls, data: Dict[str, str]) -> "Settings":
        """Parse the reference's ConfigMap keys (settings.go:52-66); raises on
        invalid values, mirroring the parse-or-panic contract."""
        kwargs = {}
        if "batchMaxDuration" in data:
            kwargs["batch_max_duration"] = _parse_duration(data["batchMaxDuration"])
        if "batchIdleDuration" in data:
            kwargs["batch_idle_duration"] = _parse_duration(data["batchIdleDuration"])
        if "featureGates.driftEnabled" in data:
            kwargs["drift_enabled"] = data["featureGates.driftEnabled"].lower() == "true"
        return cls(**kwargs)


def _parse_duration(value: str) -> float:
    """Parse Go-style durations ('10s', '1m30s', '500ms')."""
    import re

    m = re.fullmatch(
        r"((?P<h>\d+(\.\d+)?)h)?((?P<m>\d+(\.\d+)?)m)?"
        r"((?P<s>\d+(\.\d+)?)s)?((?P<ms>\d+(\.\d+)?)ms)?",
        value.strip(),
    )
    if not m or not any(m.groupdict().values()):
        raise ValueError(f"invalid duration {value!r}")
    parts = m.groupdict()
    return (
        float(parts["h"] or 0) * 3600
        + float(parts["m"] or 0) * 60
        + float(parts["s"] or 0)
        + float(parts["ms"] or 0) / 1000
    )
