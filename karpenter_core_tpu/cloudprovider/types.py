"""Cloud-provider SPI: the vendor interface and its data model.

Mirror of /root/reference/pkg/cloudprovider/types.go:50-175.  An InstanceType
is a launchable shape (requirements + capacity + per-zone/capacity-type priced
offerings); a CloudProvider can create/delete machines and enumerate the
instance-type catalog per provisioner.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import OP_IN
from karpenter_core_tpu.apis.v1alpha5 import Machine, Provisioner
from karpenter_core_tpu.scheduling import Requirements
from karpenter_core_tpu.utils import resources as resources_util


class MachineNotFoundError(Exception):
    """Raised by CloudProvider.get/delete when the machine does not exist
    (types.go:148)."""


class InsufficientCapacityError(Exception):
    """Raised by CloudProvider.create when the offering has no capacity for
    the selected instance type (the real clouds' ICE).  Deterministic for the
    caller: retrying the same instance type won't help until capacity
    returns, so launch retries should redraw from the remaining options."""

    def __init__(self, instance_type: str, message: str = "") -> None:
        super().__init__(
            message or f"insufficient capacity for instance type {instance_type!r}"
        )
        self.instance_type = instance_type


class TransientCloudError(Exception):
    """Raised by CloudProvider.create/delete for retryable API faults
    (throttling, 5xx): the same call may succeed moments later."""


@dataclass(frozen=True)
class Offering:
    """A (capacity type, zone) purchase option for an instance type
    (types.go:106).

    ``interruption_rate`` is the cloud's reclaim-probability signal for the
    offering (spot interruption frequency, [0, 1]); it seeds the policy
    subsystem's risk priors (policy.planes) and defaults to 0 so offerings
    built before the policy layer behave exactly as before."""

    capacity_type: str
    zone: str
    price: float
    available: bool = True
    interruption_rate: float = 0.0


class Offerings(List[Offering]):
    """Decorated offering list with the reference's filter helpers
    (types.go:119-145)."""

    def get(self, capacity_type: str, zone: str) -> Optional[Offering]:
        for o in self:
            if o.capacity_type == capacity_type and o.zone == zone:
                return o
        return None

    def available(self) -> "Offerings":
        return Offerings(o for o in self if o.available)

    def requirements(self, requirements: Requirements) -> "Offerings":
        return Offerings(
            o
            for o in self
            if (
                not requirements.has(labels_api.LABEL_TOPOLOGY_ZONE)
                or requirements.get(labels_api.LABEL_TOPOLOGY_ZONE).has(o.zone)
            )
            and (
                not requirements.has(labels_api.LABEL_CAPACITY_TYPE)
                or requirements.get(labels_api.LABEL_CAPACITY_TYPE).has(o.capacity_type)
            )
        )

    def cheapest(self) -> Optional[Offering]:
        return min(self, key=lambda o: o.price, default=None)


@dataclass
class InstanceType:
    name: str
    requirements: Requirements = field(default_factory=Requirements)
    offerings: Offerings = field(default_factory=Offerings)
    capacity: resources_util.ResourceList = field(default_factory=dict)
    overhead: resources_util.ResourceList = field(default_factory=dict)

    def allocatable(self) -> resources_util.ResourceList:
        """Capacity minus system overhead (types.go:87)."""
        return resources_util.subtract(self.capacity, self.overhead)

    def __post_init__(self) -> None:
        # instance types always carry their own name requirement so catalogs can
        # be filtered by node.kubernetes.io/instance-type
        if not self.requirements.has(labels_api.LABEL_INSTANCE_TYPE_STABLE):
            from karpenter_core_tpu.scheduling import Requirement

            self.requirements.add(
                Requirement(labels_api.LABEL_INSTANCE_TYPE_STABLE, OP_IN, [self.name])
            )


class CloudProvider(abc.ABC):
    """Vendor SPI (types.go:50-68)."""

    @abc.abstractmethod
    def create(self, machine: Machine) -> Machine:
        """Launch a machine; returns the resolved machine with provider id,
        capacity, and concrete labels."""

    @abc.abstractmethod
    def delete(self, machine: Machine) -> None:
        """Terminate the backing instance; raises MachineNotFoundError if gone."""

    @abc.abstractmethod
    def get_instance_types(self, provisioner: Optional[Provisioner]) -> List[InstanceType]:
        """The catalog available to the provisioner."""

    def is_machine_drifted(self, machine: Machine) -> bool:
        return False

    def name(self) -> str:
        return type(self).__name__.lower()
