"""CloudProvider metrics decorator.

Mirror of /root/reference/pkg/cloudprovider/metrics/cloudprovider.go: wraps any
CloudProvider and counts method calls (and durations) by provider/method.
"""

from __future__ import annotations

from typing import List, Optional

from karpenter_core_tpu.apis.v1alpha5 import Machine, Provisioner
from karpenter_core_tpu.cloudprovider.types import CloudProvider, InstanceType
from karpenter_core_tpu.metrics import REGISTRY, measure

METHOD_CALLS = REGISTRY.counter(
    "karpenter_cloudprovider_method_calls_total",
    "Number of cloud provider method calls.",
    ("provider", "method"),
)
METHOD_DURATION = REGISTRY.histogram(
    "karpenter_cloudprovider_duration_seconds",
    "Duration of cloud provider method calls.",
    ("provider", "method"),
)


def decorate(provider: CloudProvider) -> CloudProvider:
    return _Decorator(provider)


class _Decorator(CloudProvider):
    def __init__(self, inner: CloudProvider) -> None:
        self.inner = inner

    def _observe(self, method: str):
        METHOD_CALLS.labels(self.inner.name(), method).inc()
        return measure(METHOD_DURATION.labels(self.inner.name(), method))

    def create(self, machine: Machine) -> Machine:
        done = self._observe("Create")
        try:
            return self.inner.create(machine)
        finally:
            done()

    def delete(self, machine: Machine) -> None:
        done = self._observe("Delete")
        try:
            return self.inner.delete(machine)
        finally:
            done()

    def get_instance_types(self, provisioner: Optional[Provisioner]) -> List[InstanceType]:
        done = self._observe("GetInstanceTypes")
        try:
            return self.inner.get_instance_types(provisioner)
        finally:
            done()

    def is_machine_drifted(self, machine: Machine) -> bool:
        done = self._observe("IsMachineDrifted")
        try:
            return self.inner.is_machine_drifted(machine)
        finally:
            done()

    def name(self) -> str:
        return self.inner.name()

    def __getattr__(self, item):
        return getattr(self.inner, item)
