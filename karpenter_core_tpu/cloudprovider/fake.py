"""Fake cloud provider + deterministic instance-type catalogs for tests/benches.

Mirror of /root/reference/pkg/cloudprovider/fake/{cloudprovider.go:39-175,
instancetype.go:30-164}: records create calls, supports failure injection via
``allowed_create_calls``, selects the cheapest compatible offering on create,
and ships the incremental ``instance_types(n)`` and 1,344-type cartesian
``instance_types_assorted()`` catalogs.
"""

from __future__ import annotations

import itertools
import os
import threading
import uuid
from typing import List, Optional

from karpenter_core_tpu.apis import labels as labels_api
from karpenter_core_tpu.apis.objects import (
    OP_DOES_NOT_EXIST,
    OP_IN,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
)
from karpenter_core_tpu.apis.v1alpha5 import Machine, Provisioner
from karpenter_core_tpu.chaos import plane as chaos
from karpenter_core_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    MachineNotFoundError,
    Offering,
    Offerings,
    TransientCloudError,
)
from karpenter_core_tpu.scheduling import Requirement, Requirements
from karpenter_core_tpu.utils import resources as resources_util

# cloud.create faults: kind "error" with data.mode "insufficient-capacity"
# (optionally data.instance_types=[...] to target types) raises ICE, any
# other error raises TransientCloudError; kind "partial" makes the create
# succeed but the node never register (stillborn).  cloud.delete faults:
# code 404 raises MachineNotFoundError, otherwise TransientCloudError.
CLOUD_CREATE = chaos.point("cloud.create")
CLOUD_DELETE = chaos.point("cloud.delete")

LABEL_INSTANCE_SIZE = "size"
EXOTIC_INSTANCE_LABEL_KEY = "special"
INTEGER_INSTANCE_LABEL_KEY = "integer"
RESOURCE_GPU_VENDOR_A = "fake.com/vendor-a"
RESOURCE_GPU_VENDOR_B = "fake.com/vendor-b"

labels_api.register_well_known_labels(
    LABEL_INSTANCE_SIZE, EXOTIC_INSTANCE_LABEL_KEY, INTEGER_INSTANCE_LABEL_KEY
)

GI = float(2**30)
MI = float(2**20)


def price_from_resources(resources: resources_util.ResourceList) -> float:
    """Deterministic synthetic pricing (instancetype.go priceFromResources)."""
    price = 0.0
    for name, quantity in resources.items():
        if name == resources_util.CPU:
            price += 0.025 * quantity
        elif name == resources_util.MEMORY:
            price += 0.001 * (quantity / GI)
        elif name in (RESOURCE_GPU_VENDOR_A, RESOURCE_GPU_VENDOR_B):
            price += 1.0 * quantity
    return price


def new_instance_type(
    name: str,
    resources: Optional[resources_util.ResourceList] = None,
    offerings: Optional[List[Offering]] = None,
    architecture: str = "",
    operating_systems: Optional[List[str]] = None,
) -> InstanceType:
    resources = dict(resources or {})
    resources.setdefault(resources_util.CPU, 4.0)
    resources.setdefault(resources_util.MEMORY, 4 * GI)
    resources.setdefault(resources_util.PODS, 5.0)
    if not offerings:
        price = price_from_resources(resources)
        offerings = [
            Offering("spot", "test-zone-1", price),
            Offering("spot", "test-zone-2", price),
            Offering("on-demand", "test-zone-1", price),
            Offering("on-demand", "test-zone-2", price),
            Offering("on-demand", "test-zone-3", price),
        ]
    architecture = architecture or labels_api.ARCHITECTURE_AMD64
    operating_systems = operating_systems or ["linux", "windows", "darwin"]
    available = Offerings(offerings).available()
    requirements = Requirements(
        Requirement(labels_api.LABEL_INSTANCE_TYPE_STABLE, OP_IN, [name]),
        Requirement(labels_api.LABEL_ARCH_STABLE, OP_IN, [architecture]),
        Requirement(labels_api.LABEL_OS_STABLE, OP_IN, operating_systems),
        Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, [o.zone for o in available]),
        Requirement(labels_api.LABEL_CAPACITY_TYPE, OP_IN, [o.capacity_type for o in available]),
        Requirement(
            INTEGER_INSTANCE_LABEL_KEY, OP_IN, [str(int(resources[resources_util.CPU]))]
        ),
    )
    # DoesNotExist + insert == In semantics (complement stays False); "large"
    # instance types additionally carry the exotic label
    size = Requirement(LABEL_INSTANCE_SIZE, OP_DOES_NOT_EXIST)
    exotic = Requirement(EXOTIC_INSTANCE_LABEL_KEY, OP_DOES_NOT_EXIST)
    if resources[resources_util.CPU] > 4 and resources[resources_util.MEMORY] > 8 * GI:
        size.insert("large")
        exotic.insert("optional")
    else:
        size.insert("small")
    requirements.add(size, exotic)
    return InstanceType(
        name=name,
        requirements=requirements,
        offerings=Offerings(offerings),
        capacity=resources,
        overhead={resources_util.CPU: 0.1, resources_util.MEMORY: 10 * MI},
    )


def instance_types(total: int) -> List[InstanceType]:
    """Incrementing catalog: i vcpu / 2i Gi / 10i pods (instancetype.go:151-164)."""
    return [
        new_instance_type(
            f"fake-it-{i}",
            resources={
                resources_util.CPU: float(i + 1),
                resources_util.MEMORY: (i + 1) * 2 * GI,
                resources_util.PODS: float((i + 1) * 10),
            },
        )
        for i in range(total)
    ]


def instance_types_assorted() -> List[InstanceType]:
    """1,344-type cartesian catalog over cpu×mem×zone×capacity-type×os×arch
    (instancetype.go:109-143)."""
    out = []
    for cpu, mem, zone, ct, os_, arch in itertools.product(
        [1, 2, 4, 8, 16, 32, 64],
        [1, 2, 4, 8, 16, 32, 64, 128],
        ["test-zone-1", "test-zone-2", "test-zone-3"],
        [labels_api.CAPACITY_TYPE_SPOT, labels_api.CAPACITY_TYPE_ON_DEMAND],
        ["linux", "windows"],
        [labels_api.ARCHITECTURE_AMD64, labels_api.ARCHITECTURE_ARM64],
    ):
        resources = {
            resources_util.CPU: float(cpu),
            resources_util.MEMORY: mem * GI,
        }
        price = price_from_resources(
            {**resources, resources_util.PODS: 5.0}
        )
        out.append(
            new_instance_type(
                f"{cpu}-cpu-{mem}-mem-{arch}-{os_}-{zone}-{ct}",
                resources=resources,
                offerings=[Offering(ct, zone, price)],
                architecture=arch,
                operating_systems=[os_],
            )
        )
    return out


_node_names = itertools.count(1)
# real clouds mint globally-unique instance ids; with the durable apiserver
# backend node objects outlive the process, so a restarted operator's fresh
# counter must not re-mint a previous life's name+provider-id (the launch
# pre-create would silently adopt the stale node).  The per-process tag keeps
# within-process names deterministic and ordered while making identities
# unique across operator lifetimes.  KC_FAKE_NODE_TAG pins it (tests that
# deliberately simulate a same-identity relaunch).
_run_tag = os.environ.get("KC_FAKE_NODE_TAG") or uuid.uuid4().hex[:6]


class FakeCloudProvider(CloudProvider):
    def __init__(self, instance_types: Optional[List[InstanceType]] = None) -> None:
        self.instance_types_list = instance_types
        self.create_calls: List[Machine] = []
        self.delete_calls: List[Machine] = []
        self.allowed_create_calls = 1 << 62
        self.drifted = False
        self.next_create_error: Optional[Exception] = None
        # first-class failure modes (settable directly by tests, and driven
        # by the chaos plane's cloud.create faults):
        #   capacity_errors:  instance-type name -> remaining ICE creates
        #   transient_create_failures: next N creates raise TransientCloudError
        #   stillborn_creates: next N creates succeed but the node never
        #                      registers (provider ids land in stillborn_ids;
        #                      the harness's kubelet emulation skips them)
        self.capacity_errors: dict = {}
        self.transient_create_failures = 0
        self.stillborn_creates = 0
        self.stillborn_ids: set = set()
        self._mu = threading.Lock()
        self._created: dict = {}

    def reset(self) -> None:
        with self._mu:
            self.create_calls = []
            self.delete_calls = []
            self.allowed_create_calls = 1 << 62
            self.next_create_error = None
            self.capacity_errors = {}
            self.transient_create_failures = 0
            self.stillborn_creates = 0
            self.stillborn_ids = set()

    def created_machines(self) -> List[Machine]:
        """Machines alive at the provider — the chaos matrix's leak check
        surface (every entry must map to a live node object or be deleted)."""
        with self._mu:
            return list(self._created.values())

    def _check_create_faults(self, instance_type: InstanceType) -> bool:
        """Apply the first-class failure modes and any armed chaos fault for
        this create; returns True when the create should be stillborn."""
        stillborn = False
        with self._mu:
            remaining = self.capacity_errors.get(instance_type.name, 0)
            if remaining > 0:
                self.capacity_errors[instance_type.name] = remaining - 1
                raise InsufficientCapacityError(instance_type.name)
            if self.transient_create_failures > 0:
                self.transient_create_failures -= 1
                raise TransientCloudError("injected transient cloud API error")
            if self.stillborn_creates > 0:
                self.stillborn_creates -= 1
                stillborn = True
        # chaos fires AFTER the first-class knobs: a knob that already failed
        # this create would otherwise discard an injected (counted, traced)
        # fault, misattributing the failure in the audit
        fault = CLOUD_CREATE.hit(
            kinds=(chaos.KIND_ERROR, chaos.KIND_TIMEOUT, chaos.KIND_PARTIAL),
            instance_type=instance_type.name,
        )
        if fault is not None:
            if fault.kind == chaos.KIND_PARTIAL:
                stillborn = True
            elif fault.kind in (chaos.KIND_ERROR, chaos.KIND_TIMEOUT):
                mode = fault.data.get("mode", "transient")
                if mode == "insufficient-capacity":
                    targets = fault.data.get("instance_types")
                    if not targets or instance_type.name in targets:
                        raise InsufficientCapacityError(
                            instance_type.name, fault.message
                        )
                else:
                    raise TransientCloudError(fault.describe())
        return stillborn

    # -- offering realism knobs (policy subsystem, docs/POLICY.md) -------------

    def _pinned_catalog(self) -> List[InstanceType]:
        """The catalog as a mutable, pinned list.  ``get_instance_types``
        builds the default catalog FRESH per call when no list was supplied,
        so dynamic-offering knobs must first pin one instance of it."""
        if self.instance_types_list is None:
            self.instance_types_list = default_instance_types()
        return self.instance_types_list

    def _update_offerings(self, instance_type, capacity_type, zone, **changes) -> int:
        """Replace matching offerings (frozen dataclasses) with updated
        copies; returns how many offerings changed.  ``capacity_type`` /
        ``zone`` of None match everything."""
        import dataclasses

        updated = 0
        with self._mu:
            for it in self._pinned_catalog():
                if it.name != instance_type:
                    continue
                fresh = []
                for off in it.offerings:
                    if (capacity_type is None or off.capacity_type == capacity_type) and (
                        zone is None or off.zone == zone
                    ):
                        off = dataclasses.replace(off, **changes)
                        updated += 1
                    fresh.append(off)
                it.offerings = Offerings(fresh)
        return updated

    def set_price(
        self,
        instance_type: str,
        price: float,
        capacity_type: Optional[str] = None,
        zone: Optional[str] = None,
    ) -> int:
        """Dynamic per-offering price update (the spot market moving).  The
        policy input digest covers prices, so a set_price between reconciles
        invalidates the incremental warm-start lineage exactly like any other
        supply change (tests/test_policy.py pins the escalation)."""
        return self._update_offerings(
            instance_type, capacity_type, zone, price=float(price)
        )

    def set_interruption_rate(
        self,
        instance_type: str,
        rate: float,
        capacity_type: Optional[str] = "spot",
        zone: Optional[str] = None,
    ) -> int:
        """Stamp an interruption-risk prior on matching offerings (spot by
        default).  Feeds the policy risk planes (policy.planes) and the
        ``interrupt_spot`` sampler below."""
        return self._update_offerings(
            instance_type, capacity_type, zone,
            interruption_rate=min(max(float(rate), 0.0), 1.0),
        )

    def interrupt_spot(self, rng, creates: int = 1) -> List[str]:
        """Sample one round of spot interruptions from the per-offering
        ``interruption_rate`` priors: each spot offering with a positive rate
        is reclaimed with that probability (``rng`` is a seeded
        utils.retry.DeterministicRNG so soak runs replay), and every
        interrupted instance type feeds the first-class ``capacity_errors``
        failure path — its next ``creates`` launches raise
        InsufficientCapacityError, exactly the chaos plane's capacity-fault
        shape.  Returns the interrupted type names."""
        interrupted: List[str] = []
        with self._mu:
            for it in self._pinned_catalog():
                for off in it.offerings:
                    rate = float(getattr(off, "interruption_rate", 0.0) or 0.0)
                    if off.capacity_type != "spot" or rate <= 0.0:
                        continue
                    if rng.random() < rate:
                        self.capacity_errors[it.name] = (
                            self.capacity_errors.get(it.name, 0) + creates
                        )
                        interrupted.append(it.name)
                        break  # one ICE grant per type per round
        return interrupted

    def create(self, machine: Machine) -> Machine:
        with self._mu:
            self.create_calls.append(machine)
            if len(self.create_calls) > self.allowed_create_calls:
                raise RuntimeError("erroring as number of AllowedCreateCalls has been exceeded")
            if self.next_create_error is not None:
                err, self.next_create_error = self.next_create_error, None
                raise err

        requirements = Requirements.from_node_selector_requirements(*machine.spec.requirements)
        candidates = [
            it
            for it in self.get_instance_types(None)
            if requirements.get(labels_api.LABEL_INSTANCE_TYPE_STABLE).has(it.name)
        ]
        if not candidates:
            raise RuntimeError("no compatible instance types")

        def cheapest_price(it: InstanceType) -> float:
            offers = it.offerings.available().requirements(requirements)
            cheapest = offers.cheapest()
            return cheapest.price if cheapest else float("inf")

        candidates.sort(key=cheapest_price)
        instance_type = candidates[0]
        stillborn = self._check_create_faults(instance_type)
        labels = {}
        for key in instance_type.requirements.keys():
            requirement = instance_type.requirements.get(key)
            if requirement.operator() == OP_IN:
                labels[key] = requirement.values_list()[0]
        for offering in instance_type.offerings.available():
            compat = requirements.compatible(
                Requirements(
                    Requirement(labels_api.LABEL_TOPOLOGY_ZONE, OP_IN, [offering.zone]),
                    Requirement(labels_api.LABEL_CAPACITY_TYPE, OP_IN, [offering.capacity_type]),
                )
            )
            if compat is None:
                labels[labels_api.LABEL_TOPOLOGY_ZONE] = offering.zone
                labels[labels_api.LABEL_CAPACITY_TYPE] = offering.capacity_type
                break
        labels.update(machine.metadata.labels)
        name = f"fake-node-{_run_tag}-{next(_node_names):05d}"
        machine.status.provider_id = f"fake://{name}"
        machine.status.capacity = dict(instance_type.capacity)
        machine.status.allocatable = instance_type.allocatable()
        resolved = Machine(
            metadata=ObjectMeta(name=name, labels=labels),
            spec=machine.spec,
            status=machine.status,
        )
        with self._mu:
            self._created[machine.status.provider_id] = resolved
            if stillborn:
                self.stillborn_ids.add(machine.status.provider_id)
        return resolved

    def to_node(self, machine: Machine) -> Node:
        """Render the launched machine as the Node the kubelet would register."""
        return Node(
            metadata=ObjectMeta(name=machine.name, labels=dict(machine.metadata.labels)),
            spec=NodeSpec(provider_id=machine.status.provider_id, taints=list(machine.spec.taints)),
            status=NodeStatus(
                capacity=dict(machine.status.capacity),
                allocatable=dict(machine.status.allocatable),
            ),
        )

    def delete(self, machine: Machine) -> None:
        fault = CLOUD_DELETE.hit(
            kinds=(chaos.KIND_ERROR, chaos.KIND_TIMEOUT),
            provider_id=machine.status.provider_id,
        )
        if fault is not None and fault.kind in (chaos.KIND_ERROR, chaos.KIND_TIMEOUT):
            if fault.code == 404:
                raise MachineNotFoundError(machine.status.provider_id)
            raise TransientCloudError(fault.describe())
        with self._mu:
            self.delete_calls.append(machine)
            if machine.status.provider_id not in self._created:
                raise MachineNotFoundError(machine.status.provider_id)
            del self._created[machine.status.provider_id]
            self.stillborn_ids.discard(machine.status.provider_id)

    def get_instance_types(self, provisioner: Optional[Provisioner]) -> List[InstanceType]:
        if self.instance_types_list is not None:
            return self.instance_types_list
        return default_instance_types()

    def is_machine_drifted(self, machine: Machine) -> bool:
        return self.drifted

    def name(self) -> str:
        return "fake"


def default_instance_types() -> List[InstanceType]:
    """The reference fake's six-type default catalog (cloudprovider.go:118-155)."""
    return [
        new_instance_type("default-instance-type"),
        new_instance_type(
            "small-instance-type",
            resources={resources_util.CPU: 2.0, resources_util.MEMORY: 2 * GI},
        ),
        new_instance_type(
            "gpu-vendor-instance-type", resources={RESOURCE_GPU_VENDOR_A: 2.0}
        ),
        new_instance_type(
            "gpu-vendor-b-instance-type", resources={RESOURCE_GPU_VENDOR_B: 2.0}
        ),
        new_instance_type(
            "arm-instance-type",
            architecture=labels_api.ARCHITECTURE_ARM64,
            operating_systems=["ios", "linux", "windows", "darwin"],
            resources={resources_util.CPU: 16.0, resources_util.MEMORY: 128 * GI},
        ),
        new_instance_type("single-pod-instance-type", resources={resources_util.PODS: 1.0}),
    ]
