from karpenter_core_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    MachineNotFoundError,
    Offering,
    Offerings,
)

__all__ = ["CloudProvider", "InstanceType", "MachineNotFoundError", "Offering", "Offerings"]
