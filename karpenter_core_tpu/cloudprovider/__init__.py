from karpenter_core_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    MachineNotFoundError,
    Offering,
    Offerings,
    TransientCloudError,
)

__all__ = [
    "CloudProvider",
    "InstanceType",
    "InsufficientCapacityError",
    "MachineNotFoundError",
    "Offering",
    "Offerings",
    "TransientCloudError",
]
