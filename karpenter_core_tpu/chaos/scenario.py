"""Scenario: a declarative, seeded fault schedule for the injection plane.

A scenario names, for each injection point, *when* to fault (per-hit
probability, an explicit hit schedule, or the first N hits), *what* fault to
inject (kind, code, message, delay), and when to stop (``stop_after``).  The
schedule is a pure function of ``(seed, point, hit_index)`` — the same seed
replays the same faults in the same order, which is what makes every chaos
failure reproducible from its printed ``(scenario, seed)`` pair.

Specs come from dicts or a TOML subset (this container's Python predates
``tomllib``, so a mini-parser covers the forms docs/CHAOS.md documents):

    [scenario]
    name = "apiserver-flake"
    seed = 1234

    [points."kubeapi.put"]
    prob = 0.3
    kind = "error"
    code = 500
    stop_after = 5

    [points."cloud.create"]
    first_n = 2
    kind = "error"
    message = "insufficient capacity"

This module is the one place in the package allowed to import ``random``
(the kcanalyze chaos-hygiene determinism gate): ``random.Random`` seeded
with a derived string is a stable, platform-independent uniform source.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from karpenter_core_tpu.chaos import plane


@dataclass
class PointSpec:
    """When and how one injection point faults."""

    prob: float = 0.0  # per-hit fault probability (seed-derived)
    schedule: Optional[List[int]] = None  # explicit 0-based hit indices
    first_n: int = 0  # fault the first N hits
    kind: str = plane.KIND_ERROR
    code: int = 0
    message: str = ""
    delay_s: float = 0.0
    stop_after: int = 0  # 0 = unbounded
    data: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.kind not in plane.FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (have {plane.FAULT_KINDS})"
            )
        if self.schedule is not None:
            self.schedule = sorted(int(i) for i in self.schedule)

    def to_dict(self) -> dict:
        """The spec back in ``from_dict`` form (defaults omitted) — embedded
        in soak verdict reports so a run carries its exact fault plan."""
        out: dict = {}
        for key in ("prob", "schedule", "first_n", "code", "message",
                    "delay_s", "stop_after"):
            value = getattr(self, key)
            if value:
                out[key] = value
        if self.kind != plane.KIND_ERROR or not out:
            out["kind"] = self.kind
        if self.data:
            out["data"] = dict(self.data)
        return out


class Scenario:
    """An armable, seeded fault plan over named injection points."""

    def __init__(self, name: str, seed: int, points: Dict[str, PointSpec]) -> None:
        self.name = name
        self.seed = int(seed)
        self.points = dict(points)
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._skew_counted = False

    def __repr__(self) -> str:
        return f"Scenario(name={self.name!r}, seed={self.seed})"

    # -- construction ----------------------------------------------------------

    @classmethod
    def from_dict(cls, spec: dict) -> "Scenario":
        name = spec.get("name", "unnamed")
        seed = int(spec.get("seed", 0))
        points = {}
        for point_name, raw in (spec.get("points") or {}).items():
            points[point_name] = PointSpec(**raw)
        return cls(name, seed, points)

    @classmethod
    def from_toml(cls, text: str) -> "Scenario":
        return cls.from_dict(_parse_mini_toml(text))

    def to_dict(self) -> dict:
        """Round-trips through ``from_dict`` — the replay-workflow spec a
        soak verdict report embeds."""
        return {
            "name": self.name,
            "seed": self.seed,
            "points": {
                point: spec.to_dict() for point, spec in sorted(self.points.items())
            },
        }

    # -- the deterministic schedule --------------------------------------------

    def _uniform(self, point_name: str, index: int) -> float:
        # string-seeded Random is derived through SHA-512: stable across
        # processes and platforms, independent per (seed, point, index)
        return random.Random(f"{self.seed}:{point_name}:{index}").random()

    def would_fault(self, point_name: str, index: int) -> bool:
        """Pure schedule query (no counters): does hit ``index`` fault?"""
        spec = self.points.get(point_name)
        if spec is None:
            return False
        if spec.schedule is not None:
            return index in spec.schedule
        if spec.first_n:
            return index < spec.first_n
        if spec.prob > 0.0:
            return self._uniform(point_name, index) < spec.prob
        return False

    def fault_schedule(self, point_name: str, n_hits: int) -> List[int]:
        """The hit indices among the first ``n_hits`` that fault — the
        replayable schedule tests assert on."""
        out = [i for i in range(n_hits) if self.would_fault(point_name, i)]
        spec = self.points.get(point_name)
        if spec is not None and spec.stop_after:
            out = out[: spec.stop_after]
        return out

    def decide(self, point_name: str, kinds=None) -> Optional[plane.Fault]:
        """Called by Point.hit while this scenario is armed: consume one hit
        index and return the fault for it, if any.  ``kinds`` is the set the
        call site (plus the plane itself, for latency) can interpret: a spec
        kind outside it is discarded without firing — the hit index still
        advances (schedule determinism is a pure function of the index), but
        neither ``fired_counts`` nor the injected-fault metrics move, so the
        audit never reports an injection nothing acted on."""
        spec = self.points.get(point_name)
        if spec is None:
            return None
        with self._lock:
            index = self._hits.get(point_name, 0)
            self._hits[point_name] = index + 1
            if kinds is not None and spec.kind not in kinds:
                return None
            if spec.stop_after and self._fired.get(point_name, 0) >= spec.stop_after:
                return None
            if not self.would_fault(point_name, index):
                return None
            self._fired[point_name] = self._fired.get(point_name, 0) + 1
        return plane.Fault(
            point=point_name,
            index=index,
            kind=spec.kind,
            code=spec.code,
            message=spec.message or f"injected {spec.kind}",
            delay_s=spec.delay_s,
            data=dict(spec.data),
        )

    def clock_skew_s(self) -> float:
        """Standing clock offset (the ``clock.skew`` point's delay_s)."""
        spec = self.points.get("clock.skew")
        if spec is None or spec.kind != plane.KIND_SKEW:
            return 0.0
        with self._lock:
            if not self._skew_counted:
                self._skew_counted = True
                plane.CHAOS_FAULTS_INJECTED.labels("clock.skew", spec.kind).inc()
        return spec.delay_s

    # -- bookkeeping -----------------------------------------------------------

    def reset_counters(self) -> None:
        with self._lock:
            self._hits = {}
            self._fired = {}
            self._skew_counted = False

    def hit_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)

    def fired_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired)


# -- mini-TOML ----------------------------------------------------------------


def _coerce(value: str):
    value = value.strip()
    if value.startswith("[") and value.endswith("]"):
        inner = value[1:-1].strip()
        return [_coerce(v) for v in inner.split(",")] if inner else []
    if value.startswith('"') and value.endswith('"'):
        return value[1:-1]
    if value in ("true", "false"):
        return value == "true"
    try:
        return int(value)
    except ValueError:
        pass
    try:
        return float(value)
    except ValueError:
        return value


def _strip_comment(line: str) -> str:
    """Drop a trailing ``#`` comment, but only outside double quotes — a
    fault message like ``"quota #429 exceeded"`` must survive intact."""
    in_string = False
    for i, ch in enumerate(line):
        if ch == '"':
            in_string = not in_string
        elif ch == "#" and not in_string:
            return line[:i]
    return line


def _parse_mini_toml(text: str) -> dict:
    """[scenario] / [points."name"] tables with scalar and list values —
    exactly the subset docs/CHAOS.md documents (this Python has no tomllib)."""
    out: dict = {"points": {}}
    target: Optional[dict] = None
    for raw_line in text.splitlines():
        line = _strip_comment(raw_line).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            header = line[1:-1].strip()
            if header == "scenario":
                target = out
            elif header.startswith("points."):
                point_name = header[len("points."):].strip().strip('"')
                target = out["points"].setdefault(point_name, {})
            else:
                raise ValueError(f"unknown scenario table [{header}]")
            continue
        if "=" not in line or target is None:
            raise ValueError(f"unparseable scenario line {raw_line!r}")
        key, _, value = line.partition("=")
        target[key.strip()] = _coerce(value)
    return out
