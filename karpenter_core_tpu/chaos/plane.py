"""The injection plane: process-global, default-off named fault points.

Production modules register points at import time —

    CLOUD_CREATE = chaos.point("cloud.create")

— and fire them on the guarded operation:

    fault = CLOUD_CREATE.hit(kinds=(chaos.KIND_ERROR, ...), instance_type=it.name)
    if fault is not None:
        ...interpret the fault (raise the site's native error type)...

``kinds`` declares which fault kinds the site can interpret.  A scenario
kind the site cannot act on is discarded BEFORE it is counted, traced, or
logged — otherwise a misconfigured scenario (e.g. kind="partial" on
``kubeapi.put``) would report full injected-fault coverage while injecting
nothing.  Latency is implicitly supported whenever an armed clock exists,
because the plane applies the sleep itself.

A hit is a zero-cost no-op (one global load + is-None check) unless a
``Scenario`` is armed, so the points can live on hot paths.  When armed, the
scenario decides — deterministically from its seed and the point's hit index
— whether this hit faults; a triggered fault increments
``karpenter_chaos_faults_injected_total{point,kind}`` and lands a
``chaos.fault`` event on the active tracing span, so a decision audit shows
*which* injected fault caused *which* decision.  Latency-kind faults are
applied here (sleep through the armed clock); every other kind is returned
for the call site to interpret, because only the site knows its native error
surface (ConflictError vs ApiServerError vs RuntimeError).

Registration is exactly-once per name (enforced at runtime here and
statically by the kcanalyze ``chaos-hygiene`` pass); call sites that share a
point import the registered ``Point`` object.  See docs/CHAOS.md for the
point catalog and how to add one.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from karpenter_core_tpu import tracing
from karpenter_core_tpu.metrics import REGISTRY

log = logging.getLogger(__name__)

CHAOS_FAULTS_INJECTED = REGISTRY.counter(
    "karpenter_chaos_faults_injected_total",
    "Faults injected by the chaos plane, by point and fault kind.",
    ("point", "kind"),
)
CHAOS_ARMED = REGISTRY.gauge(
    "karpenter_chaos_armed",
    "1 while a chaos scenario is armed in this process.",
)

# fault kinds (scenario.py validates against this set)
KIND_ERROR = "error"
KIND_LATENCY = "latency"
KIND_TIMEOUT = "timeout"
KIND_PARTIAL = "partial"
KIND_DUPLICATE = "duplicate"
KIND_SKEW = "skew"
# a silent stall (the r02–r05 relay failure shape): interpreted only by the
# watchdog's monitored dispatch sites (utils/watchdog.py, point solver.hang)
# — the call blocks for delay_s (0 = until abandoned) instead of erroring
KIND_HANG = "hang"
FAULT_KINDS = (
    KIND_ERROR, KIND_LATENCY, KIND_TIMEOUT, KIND_PARTIAL, KIND_DUPLICATE,
    KIND_SKEW, KIND_HANG,
)


@dataclass
class Fault:
    """One injected fault, as decided by the armed scenario."""

    point: str
    index: int  # 0-based hit index at this point within the armed scenario
    kind: str = KIND_ERROR
    code: int = 0  # HTTP-ish status for error kinds (409, 410, 500, ...)
    message: str = ""
    delay_s: float = 0.0  # latency kinds; also skew offset for clock.skew
    data: dict = field(default_factory=dict)  # site-specific knobs

    def describe(self) -> str:
        detail = f" code={self.code}" if self.code else ""
        return f"chaos[{self.point}#{self.index}] {self.kind}{detail}: {self.message}"


class InjectedFault(Exception):
    """Raised by call sites that have no more specific error surface."""

    def __init__(self, fault: Fault) -> None:
        super().__init__(fault.describe())
        self.fault = fault


_lock = threading.Lock()
_points: Dict[str, "Point"] = {}
_armed = None  # Optional[Scenario]; module-global for the fast no-op path
_armed_clock = None


class Point:
    """A named injection point.  ``hit()`` is the only hot-path surface."""

    def __init__(self, name: str) -> None:
        self.name = name

    def hit(self, kinds=None, **ctx) -> Optional[Fault]:
        scenario = _armed
        if scenario is None:
            return None
        return self._hit_armed(scenario, kinds, ctx)

    def _hit_armed(self, scenario, kinds, ctx: dict) -> Optional[Fault]:
        # the effective filter: kinds the site interprets, plus latency when
        # the plane can apply it (armed clock), never latency when it can't —
        # a kind nobody can act on must not be reported as injected
        supported = set(kinds) if kinds is not None else set(FAULT_KINDS)
        if _armed_clock is not None:
            supported.add(KIND_LATENCY)
        else:
            supported.discard(KIND_LATENCY)
        fault = scenario.decide(self.name, supported)
        if fault is None:
            return None
        CHAOS_FAULTS_INJECTED.labels(self.name, fault.kind).inc()
        tracing.add_event(
            "chaos.fault",
            point=self.name,
            kind=fault.kind,
            index=fault.index,
            code=fault.code,
            scenario=scenario.name,
            seed=scenario.seed,
            **{k: v for k, v in ctx.items() if isinstance(v, (str, int, float, bool))},
        )
        log.info(
            "chaos: injecting %s (scenario=%s seed=%s)",
            fault.describe(), scenario.name, scenario.seed,
        )
        if fault.kind == KIND_LATENCY and fault.delay_s > 0:
            clock = _armed_clock
            if clock is not None:
                clock.sleep(fault.delay_s)
        return fault


def point(name: str) -> Point:
    """Register (exactly once) and return the named injection point."""
    with _lock:
        if name in _points:
            raise ValueError(f"chaos point {name!r} registered twice")
        p = _points[name] = Point(name)
        return p


def registered_points() -> Dict[str, Point]:
    with _lock:
        return dict(_points)


def arm(scenario, clock=None) -> None:
    """Arm the scenario process-wide.  ``clock`` (utils/clock.Clock) drives
    latency faults and lets FakeClock suites absorb injected delays."""
    global _armed, _armed_clock
    with _lock:
        scenario.reset_counters()
        _armed = scenario
        _armed_clock = clock
    CHAOS_ARMED.labels().set(1.0)
    log.info(
        "chaos: armed scenario=%s seed=%s points=%s — replay with this "
        "(scenario, seed) pair", scenario.name, scenario.seed,
        sorted(scenario.points),
    )


def disarm() -> None:
    global _armed, _armed_clock
    with _lock:
        _armed = None
        _armed_clock = None
    CHAOS_ARMED.labels().set(0.0)


def armed_scenario():
    return _armed


class armed:
    """``with chaos.armed(scenario, clock):`` — arm for the block only."""

    def __init__(self, scenario, clock=None) -> None:
        self.scenario = scenario
        self.clock = clock

    def __enter__(self):
        arm(self.scenario, self.clock)
        return self.scenario

    def __exit__(self, *exc) -> None:
        disarm()


def current_skew_s() -> float:
    """The armed scenario's clock-skew offset (0.0 unarmed) — read by
    utils/clock.Clock on every ``now()``.  Skew is a standing offset rather
    than a per-hit fault: clocks are read far too often to count usefully,
    so the fault counter is bumped once at first application instead."""
    scenario = _armed
    if scenario is None:
        return 0.0
    return scenario.clock_skew_s()
