"""chaos/: the deterministic fault-injection plane.

``chaos.point(name)`` registers a named injection point (exactly once);
``chaos.arm(Scenario(...))`` turns the process's points live.  Unarmed, every
point is a zero-cost no-op — production binaries never pay for the plane.
See docs/CHAOS.md for the point catalog, scenario format, and the seed-replay
workflow.
"""

from karpenter_core_tpu.chaos.plane import (
    CHAOS_FAULTS_INJECTED,
    FAULT_KINDS,
    Fault,
    InjectedFault,
    Point,
    arm,
    armed,
    armed_scenario,
    current_skew_s,
    disarm,
    point,
    registered_points,
)
from karpenter_core_tpu.chaos.scenario import PointSpec, Scenario

__all__ = [
    "CHAOS_FAULTS_INJECTED",
    "FAULT_KINDS",
    "Fault",
    "InjectedFault",
    "Point",
    "PointSpec",
    "Scenario",
    "arm",
    "armed",
    "armed_scenario",
    "current_skew_s",
    "disarm",
    "point",
    "registered_points",
]
