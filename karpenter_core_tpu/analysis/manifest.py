"""The retrace-budget manifest: one loader shared by every consumer.

tests/conftest.py (per-test budget enforcement), bench.py (cold-compile
warning), and tools/perfgate.py (post-bench re-check) all read the same
checked-in file; keeping the path and the degrade-to-empty error policy in
one place means moving or re-shaping the manifest is a one-file edit.
Stdlib-only and safe to import before any backend decision.
"""

from __future__ import annotations

import json
import os

MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "retrace_budget.json"
)


def load_retrace_manifest() -> dict:
    """The parsed manifest, or {} when missing/unreadable — budget checks
    degrade to advisory-off rather than breaking the caller."""
    try:
        with open(MANIFEST_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
