"""Framework core: source loading, the finding model, and the baseline.

Everything here is stdlib-only (``ast`` + file IO): the analyses parse the
tree, they never import it, so a pass can run against any directory —
including the temp trees the unit tests seed with known-bad fragments.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    """One analysis result, renderable as ``file:line: pass/rule: detail``."""

    path: str  # root-relative, forward slashes
    line: int
    rule: str
    detail: str
    pass_name: str = ""
    symbol: str = ""  # enclosing function/class qualname when known

    def render(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        prefix = f"{self.pass_name}/{self.rule}" if self.pass_name else self.rule
        return f"{self.path}:{self.line}: {prefix}: {self.detail}{where}"


@dataclass
class SourceModule:
    """One parsed source file."""

    name: str  # dotted module name ("" for non-package files like bench.py)
    path: Path
    relpath: str  # root-relative, forward slashes
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)

    @property
    def in_package(self) -> bool:
        return bool(self.name)


class Project:
    """The loaded analysis target: a package tree plus auxiliary roots.

    ``root`` is the repository root; ``package`` the importable package
    directory under it.  ``extra_roots`` (tests/, tools/, top-level scripts)
    participate only in passes that opt into ``all_modules`` — the
    call-graph and lock passes look at ``package_modules`` alone.
    """

    DEFAULT_EXTRA_ROOTS = ("tests", "tools", "bench.py", "__graft_entry__.py")

    def __init__(
        self,
        root: Path,
        package: str = "karpenter_core_tpu",
        extra_roots: Optional[Iterable[str]] = None,
    ) -> None:
        self.root = Path(root)
        self.package = package
        self.package_modules: List[SourceModule] = []
        self.extra_modules: List[SourceModule] = []
        self.errors: List[Finding] = []  # syntax errors surface as findings
        self._by_name: Dict[str, SourceModule] = {}

        pkg_dir = self.root / package
        if pkg_dir.is_dir():
            for path in sorted(pkg_dir.rglob("*.py")):
                if "__pycache__" in path.parts:
                    continue
                mod = self._load(path, self._dotted_name(path))
                if mod is not None:
                    self.package_modules.append(mod)
                    self._by_name[mod.name] = mod
        extras = (
            self.DEFAULT_EXTRA_ROOTS if extra_roots is None else tuple(extra_roots)
        )
        for rel in extras:
            p = self.root / rel
            if p.is_file():
                mod = self._load(p, "")
                if mod is not None:
                    self.extra_modules.append(mod)
            elif p.is_dir():
                for path in sorted(p.rglob("*.py")):
                    if "__pycache__" in path.parts:
                        continue
                    mod = self._load(path, "")
                    if mod is not None:
                        self.extra_modules.append(mod)

    @property
    def all_modules(self) -> List[SourceModule]:
        return self.package_modules + self.extra_modules

    def get(self, dotted: str) -> Optional[SourceModule]:
        return self._by_name.get(dotted)

    def relative(self, path: Path) -> str:
        try:
            return path.relative_to(self.root).as_posix()
        except ValueError:
            return path.as_posix()

    def _dotted_name(self, path: Path) -> str:
        rel = path.relative_to(self.root).with_suffix("")
        parts = list(rel.parts)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def _load(self, path: Path, name: str) -> Optional[SourceModule]:
        try:
            source = path.read_text()
        except OSError as e:
            self.errors.append(
                Finding(self.relative(path), 0, "read-error", str(e), "loader")
            )
            return None
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError as e:
            self.errors.append(
                Finding(
                    self.relative(path), e.lineno or 0, "syntax-error",
                    e.msg or "invalid syntax", "loader",
                )
            )
            return None
        return SourceModule(
            name=name, path=path, relpath=self.relative(path),
            source=source, tree=tree, lines=source.splitlines(),
        )


# -- baseline -----------------------------------------------------------------


class BaselineError(Exception):
    """Malformed baseline file (policy violations are hard errors: an
    undocumented suppression must not silently disable a gate)."""


_KV_RE = re.compile(r"^([A-Za-z_][A-Za-z0-9_-]*)\s*=\s*(.+?)\s*$")


def _parse_toml_value(raw: str, path: str, lineno: int):
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        return raw[1:-1]
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        raise BaselineError(
            f"{path}:{lineno}: unsupported TOML value {raw!r} "
            "(this parser takes strings, integers, and booleans)"
        )


def parse_mini_toml(text: str, path: str = "<baseline>") -> List[dict]:
    """Parse the ``[[suppress]]`` array-of-tables subset of TOML used by the
    baseline file (Python 3.10 has no ``tomllib``).  Inline comments are
    supported outside strings."""
    entries: List[dict] = []
    current: Optional[dict] = None
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped == "[[suppress]]":
            current = {"_line": lineno}
            entries.append(current)
            continue
        if stripped.startswith("["):
            raise BaselineError(
                f"{path}:{lineno}: only [[suppress]] tables are supported"
            )
        m = _KV_RE.match(stripped)
        if m is None:
            raise BaselineError(f"{path}:{lineno}: unparseable line {stripped!r}")
        if current is None:
            raise BaselineError(
                f"{path}:{lineno}: key outside a [[suppress]] table"
            )
        key, raw = m.group(1), m.group(2)
        if raw.startswith('"'):
            # strip a trailing comment after the closing quote (values do
            # not contain escaped quotes in this subset)
            end = raw.find('"', 1)
            if end != -1:
                rest = raw[end + 1:].strip()
                if rest and not rest.startswith("#"):
                    raise BaselineError(
                        f"{path}:{lineno}: trailing characters after string "
                        f"value: {rest!r}"
                    )
                raw = raw[: end + 1]
        else:
            raw = raw.split("#", 1)[0].strip()
        current[key] = _parse_toml_value(raw, path, lineno)
    return entries


class Baseline:
    """Checked-in suppression list.  Every entry names the pass/rule/file it
    covers and MUST carry a ``reason`` — the policy is documented false
    positives, not silenced true positives (docs/ANALYSIS.md)."""

    MATCH_KEYS = ("pass", "rule", "file", "line", "symbol", "contains")

    def __init__(self, entries: List[dict], path: str = "<baseline>") -> None:
        self.path = path
        self.entries = entries
        self.hits = [0] * len(entries)
        for e in entries:
            if not str(e.get("reason", "")).strip():
                raise BaselineError(
                    f"{path}:{e.get('_line', 0)}: suppression without a reason "
                    "(every baseline entry must document why it is a false "
                    "positive or an accepted deviation)"
                )
            unknown = set(e) - set(self.MATCH_KEYS) - {"reason", "_line"}
            if unknown:
                raise BaselineError(
                    f"{path}:{e.get('_line', 0)}: unknown key(s) "
                    f"{sorted(unknown)}"
                )

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        return cls(parse_mini_toml(path.read_text(), str(path)), str(path))

    @classmethod
    def empty(cls) -> "Baseline":
        return cls([], "<empty>")

    def match(self, finding: Finding) -> Optional[str]:
        """The matching entry's reason, or None when the finding stands."""
        for i, e in enumerate(self.entries):
            if e.get("pass") not in (None, finding.pass_name):
                continue
            if e.get("rule") not in (None, finding.rule):
                continue
            if e.get("file") not in (None, finding.path):
                continue
            if e.get("line") not in (None, finding.line):
                continue
            if e.get("symbol") not in (None, finding.symbol):
                continue
            contains = e.get("contains")
            if contains is not None and contains not in finding.detail:
                continue
            self.hits[i] += 1
            return str(e["reason"])
        return None

    def unused(self) -> List[dict]:
        return [e for e, n in zip(self.entries, self.hits) if n == 0]


def apply_baseline(
    findings: List[Finding], baseline: Baseline
) -> Tuple[List[Finding], List[Tuple[Finding, str]]]:
    """(kept, [(suppressed, reason)])."""
    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    for f in findings:
        reason = baseline.match(f)
        if reason is None:
            kept.append(f)
        else:
            suppressed.append((f, reason))
    return kept, suppressed


# -- shared ast helpers -------------------------------------------------------


def dotted(node: ast.expr) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_map(tree: ast.Module) -> Dict[str, str]:
    """local name -> dotted target for every top-level-visible import.
    ``import a.b as c`` maps c->a.b; ``from a import b`` maps b->a.b;
    ``import a.b`` maps a->a (the bound name is the root package)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    out[alias.asname] = alias.name
                else:
                    out[alias.name.split(".")[0]] = alias.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.module is None or node.level:
                continue  # relative imports are not used in this repo
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return out


def resolve_call_root(call_func: ast.expr, imports: Dict[str, str]) -> Optional[str]:
    """Fully-resolved dotted name of a call target, through the import map:
    ``mask_ops.compatible`` -> ``karpenter_core_tpu.ops.masks.compatible``."""
    name = dotted(call_func)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    target = imports.get(head)
    if target is None:
        return name
    return f"{target}.{rest}" if rest else target
