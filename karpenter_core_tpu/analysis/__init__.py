"""Static analysis framework for the repo's domain-specific bug classes.

The role golangci-lint + ``go vet -race`` play in the reference presubmit
(Makefile:16-24) cannot be vendored here, and the generic hygiene rules in
the old ``tools/lint.py`` walker knew nothing about the two failure modes
that actually hurt this codebase: a host sync silently turning the 1.27 s
warm solve back into a 30 s retrace (PR 3), and latent lock-order bugs in
the threaded operator surfacing only by accident (PR 2).  This package is a
small reusable stdlib-``ast`` framework — module loader (`core.Project`),
call-graph builder (`callgraph.CallGraph`), a per-pass `core.Finding` model
with file:line output, and a checked-in baseline/suppression file
(`baseline.toml`, parsed by `core.Baseline`) — plus the passes under
``analysis/passes/``:

  trace-safety    host-sync / trace-breaking patterns reachable from
                  ``jax.jit`` entry points
  retrace-budget  static_argnums/static_argnames consistency with the
                  compile-cache key, unhashable static args, per-call
                  ``jax.jit`` construction
  lock-order      inconsistent pairwise lock acquisition order, blocking
                  calls under a held lock, raw ``.acquire()``
  hygiene         the old lint.py rules plus assert-in-package and
                  wallclock (Clock discipline)
  instrumented    every controller ``reconcile`` opens a tracing span

Driven by ``tools/kcanalyze.py`` from ``make verify``; see docs/ANALYSIS.md
for the pass catalog, baseline policy, and how to add a pass.
"""

from karpenter_core_tpu.analysis.core import (  # noqa: F401 - public surface
    Baseline,
    BaselineError,
    Finding,
    Project,
    SourceModule,
)
from karpenter_core_tpu.analysis.callgraph import CallGraph  # noqa: F401

__all__ = [
    "Baseline",
    "BaselineError",
    "CallGraph",
    "Finding",
    "Project",
    "SourceModule",
]
