"""Discovery of ``jax.jit`` sites and their static-argument declarations.

Shared by the trace-safety pass (jit targets seed reachability) and the
retrace-budget pass (each site's static_argnums/static_argnames is checked
against the compile-cache key).  Handles the spellings this repo uses:

    @jax.jit
    @functools.partial(jax.jit, static_argnames=(...))
    jax.jit(fn, ...)
    jax.jit(lambda ...: ..., ...)
    jax.jit(jax.vmap(fn), ...)
    functools.partial(jax.jit, ...)(fn)

Targets unwrap through ``vmap``/``partial`` chains to the underlying
function expression.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.analysis.core import (
    SourceModule,
    import_map,
    resolve_call_root,
)

_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_SHARD_MAP_NAMES = {
    "jax.experimental.shard_map.shard_map",
    "jax.shard_map",
    "shard_map",
}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_UNWRAP_NAMES = {
    "jax.vmap", "vmap", "jax.checkpoint", "jax.remat",
    # a jitted shard_map unwraps to its body for reachability: host syncs
    # inside sharded bodies are trace hazards exactly like under plain jit
    "jax.experimental.shard_map.shard_map", "jax.shard_map", "shard_map",
}


@dataclass
class JitSite:
    module: SourceModule
    lineno: int
    target: Optional[ast.expr]  # function expression (Name/Attribute/Lambda)
    decorated: Optional[ast.AST] = None  # FunctionDef when a decorator site
    static_argnames: Optional[Tuple[str, ...]] = None
    static_argnums: Optional[Tuple[int, ...]] = None
    non_literal_statics: bool = False  # statics computed, not literal
    enclosing: str = ""  # qualname of the function containing the site ("" = module scope)
    jit_call: Optional[ast.Call] = None
    kwargs: Dict[str, ast.expr] = field(default_factory=dict)


def _literal_names(node: ast.expr) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _literal_nums(node: ast.expr) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _unwrap_target(
    expr: ast.expr, imports: Dict[str, str], tree: Optional[ast.Module] = None
) -> ast.expr:
    """Peel vmap/partial wrappers down to the wrapped function expression.
    A bare Name is chased through (single-assignment) local bindings so
    ``grid = jax.vmap(one_cell); jax.jit(grid)`` still yields ``one_cell``."""
    for _ in range(8):  # bounded: pathological chains just stop resolving
        if isinstance(expr, ast.Call):
            root = resolve_call_root(expr.func, imports)
            if (root in _UNWRAP_NAMES or root in _PARTIAL_NAMES) and expr.args:
                expr = expr.args[0]
                continue
            return expr
        if isinstance(expr, ast.Name) and tree is not None:
            bound = _assignment_value(tree, expr.id)
            if bound is not None and isinstance(bound, ast.Call):
                expr = bound
                continue
        return expr
    return expr


def _assignment_value(tree: ast.Module, name: str) -> Optional[ast.expr]:
    """Value of the single ``name = <expr>`` assignment in the module, or
    None when the name is unassigned or assigned more than once."""
    hits: List[ast.expr] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                hits.append(node.value)
    return hits[0] if len(hits) == 1 else None


def _apply_statics(site: JitSite, call: ast.Call) -> None:
    for kw in call.keywords:
        if kw.arg is None:
            continue
        site.kwargs[kw.arg] = kw.value
        if kw.arg == "static_argnames":
            names = _literal_names(kw.value)
            if names is None:
                site.non_literal_statics = True
            else:
                site.static_argnames = names
        elif kw.arg == "static_argnums":
            nums = _literal_nums(kw.value)
            if nums is None:
                site.non_literal_statics = True
            else:
                site.static_argnums = nums


def _is_partial_of_jit(call: ast.Call, imports: Dict[str, str]) -> bool:
    root = resolve_call_root(call.func, imports)
    if root not in _PARTIAL_NAMES or not call.args:
        return False
    return resolve_call_root(call.args[0], imports) in _JIT_NAMES


def _enclosing_map(tree: ast.Module) -> Dict[int, str]:
    """node id -> qualname of the enclosing function ("" = module scope) —
    the per-call-construction checks need to know which function a jit/
    shard_map site lives in.  Shared by find_jit_sites and
    find_shard_map_sites so the tracking can never drift between them."""
    enclosing_of: Dict[int, str] = {}

    def mark(node: ast.AST, qual: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(child, qual + [child.name])
            elif isinstance(child, ast.ClassDef):
                mark(child, qual + [child.name])
            else:
                enclosing_of[id(child)] = ".".join(qual)
                mark(child, qual)

    mark(tree, [])
    return enclosing_of


def find_jit_sites(module: SourceModule) -> List[JitSite]:
    imports = import_map(module.tree)
    sites: List[JitSite] = []
    enclosing_of = _enclosing_map(module.tree)

    for node in ast.walk(module.tree):
        # decorator sites
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                root = resolve_call_root(
                    dec.func if isinstance(dec, ast.Call) else dec, imports
                )
                if root in _JIT_NAMES:
                    site = JitSite(
                        module=module, lineno=node.lineno, target=None,
                        decorated=node,
                        enclosing=enclosing_of.get(id(node), ""),
                    )
                    if isinstance(dec, ast.Call):
                        site.jit_call = dec
                        _apply_statics(site, dec)
                    sites.append(site)
                elif isinstance(dec, ast.Call) and _is_partial_of_jit(dec, imports):
                    site = JitSite(
                        module=module, lineno=node.lineno, target=None,
                        decorated=node, jit_call=dec,
                        enclosing=enclosing_of.get(id(node), ""),
                    )
                    _apply_statics(site, dec)
                    sites.append(site)
            continue
        if not isinstance(node, ast.Call):
            continue
        root = resolve_call_root(node.func, imports)
        if root in _JIT_NAMES and node.args:
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _apply_statics(site, node)
            sites.append(site)
        elif (
            isinstance(node.func, ast.Call)
            and _is_partial_of_jit(node.func, imports)
            and node.args
        ):
            # partial(jax.jit, ...)(fn)
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node.func,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _apply_statics(site, node.func)
            sites.append(site)
    return sites


def _shard_map_kwargs(site: JitSite, call: ast.Call) -> None:
    """Record shard_map's config expressions (mesh/in_specs/out_specs/
    check_rep) on the site.  ``mesh`` may also arrive positionally (arg 1 of
    the direct-call spelling)."""
    for kw in call.keywords:
        if kw.arg:
            site.kwargs[kw.arg] = kw.value
    if "mesh" not in site.kwargs and len(call.args) >= 2:
        site.kwargs["mesh"] = call.args[1]


def find_shard_map_sites(module: SourceModule) -> List[JitSite]:
    """``shard_map`` call sites, same spellings as ``find_jit_sites``:

        shard_map(body, mesh=..., in_specs=..., out_specs=...)
        @functools.partial(shard_map, mesh=..., ...)
        functools.partial(shard_map, mesh=...)(body)

    Shared by trace-safety (sharded bodies seed jit reachability — a host
    sync inside one hangs/retraces exactly like under plain jit) and
    retrace-budget (per-call construction + un-keyed mesh statics,
    docs/ANALYSIS.md)."""
    imports = import_map(module.tree)
    sites: List[JitSite] = []
    enclosing_of = _enclosing_map(module.tree)

    def _is_partial_of_shard_map(call: ast.Call) -> bool:
        if resolve_call_root(call.func, imports) not in _PARTIAL_NAMES:
            return False
        return bool(call.args) and (
            resolve_call_root(call.args[0], imports) in _SHARD_MAP_NAMES
        )

    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call) and (
                    resolve_call_root(dec.func, imports) in _SHARD_MAP_NAMES
                    or _is_partial_of_shard_map(dec)
                ):
                    site = JitSite(
                        module=module, lineno=node.lineno, target=None,
                        decorated=node, jit_call=dec,
                        enclosing=enclosing_of.get(id(node), ""),
                    )
                    _shard_map_kwargs(site, dec)
                    sites.append(site)
            continue
        if not isinstance(node, ast.Call):
            continue
        root = resolve_call_root(node.func, imports)
        if root in _SHARD_MAP_NAMES and node.args:
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _shard_map_kwargs(site, node)
            sites.append(site)
        elif (
            isinstance(node.func, ast.Call)
            and _is_partial_of_shard_map(node.func)
            and node.args
        ):
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node.func,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _shard_map_kwargs(site, node.func)
            sites.append(site)
    return sites
