"""Discovery of ``jax.jit`` sites and their static-argument declarations.

Shared by the trace-safety pass (jit targets seed reachability) and the
retrace-budget pass (each site's static_argnums/static_argnames is checked
against the compile-cache key).  Handles the spellings this repo uses:

    @jax.jit
    @functools.partial(jax.jit, static_argnames=(...))
    jax.jit(fn, ...)
    jax.jit(lambda ...: ..., ...)
    jax.jit(jax.vmap(fn), ...)
    functools.partial(jax.jit, ...)(fn)

Targets unwrap through ``vmap``/``partial`` chains to the underlying
function expression.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from karpenter_core_tpu.analysis.core import (
    SourceModule,
    import_map,
    resolve_call_root,
)

_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_UNWRAP_NAMES = {"jax.vmap", "vmap", "jax.checkpoint", "jax.remat"}


@dataclass
class JitSite:
    module: SourceModule
    lineno: int
    target: Optional[ast.expr]  # function expression (Name/Attribute/Lambda)
    decorated: Optional[ast.AST] = None  # FunctionDef when a decorator site
    static_argnames: Optional[Tuple[str, ...]] = None
    static_argnums: Optional[Tuple[int, ...]] = None
    non_literal_statics: bool = False  # statics computed, not literal
    enclosing: str = ""  # qualname of the function containing the site ("" = module scope)
    jit_call: Optional[ast.Call] = None
    kwargs: Dict[str, ast.expr] = field(default_factory=dict)


def _literal_names(node: ast.expr) -> Optional[Tuple[str, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _literal_nums(node: ast.expr) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, int)):
                return None
            out.append(el.value)
        return tuple(out)
    return None


def _unwrap_target(
    expr: ast.expr, imports: Dict[str, str], tree: Optional[ast.Module] = None
) -> ast.expr:
    """Peel vmap/partial wrappers down to the wrapped function expression.
    A bare Name is chased through (single-assignment) local bindings so
    ``grid = jax.vmap(one_cell); jax.jit(grid)`` still yields ``one_cell``."""
    for _ in range(8):  # bounded: pathological chains just stop resolving
        if isinstance(expr, ast.Call):
            root = resolve_call_root(expr.func, imports)
            if (root in _UNWRAP_NAMES or root in _PARTIAL_NAMES) and expr.args:
                expr = expr.args[0]
                continue
            return expr
        if isinstance(expr, ast.Name) and tree is not None:
            bound = _assignment_value(tree, expr.id)
            if bound is not None and isinstance(bound, ast.Call):
                expr = bound
                continue
        return expr
    return expr


def _assignment_value(tree: ast.Module, name: str) -> Optional[ast.expr]:
    """Value of the single ``name = <expr>`` assignment in the module, or
    None when the name is unassigned or assigned more than once."""
    hits: List[ast.expr] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name:
                hits.append(node.value)
    return hits[0] if len(hits) == 1 else None


def _apply_statics(site: JitSite, call: ast.Call) -> None:
    for kw in call.keywords:
        if kw.arg is None:
            continue
        site.kwargs[kw.arg] = kw.value
        if kw.arg == "static_argnames":
            names = _literal_names(kw.value)
            if names is None:
                site.non_literal_statics = True
            else:
                site.static_argnames = names
        elif kw.arg == "static_argnums":
            nums = _literal_nums(kw.value)
            if nums is None:
                site.non_literal_statics = True
            else:
                site.static_argnums = nums


def _is_partial_of_jit(call: ast.Call, imports: Dict[str, str]) -> bool:
    root = resolve_call_root(call.func, imports)
    if root not in _PARTIAL_NAMES or not call.args:
        return False
    return resolve_call_root(call.args[0], imports) in _JIT_NAMES


def find_jit_sites(module: SourceModule) -> List[JitSite]:
    imports = import_map(module.tree)
    sites: List[JitSite] = []

    # enclosing-function tracking for the per-call-jit check
    enclosing_of: Dict[int, str] = {}

    def mark(node: ast.AST, qual: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mark(child, qual + [child.name])
            elif isinstance(child, ast.ClassDef):
                mark(child, qual + [child.name])
            else:
                enclosing_of[id(child)] = ".".join(qual)
                mark(child, qual)

    mark(module.tree, [])

    for node in ast.walk(module.tree):
        # decorator sites
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                root = resolve_call_root(
                    dec.func if isinstance(dec, ast.Call) else dec, imports
                )
                if root in _JIT_NAMES:
                    site = JitSite(
                        module=module, lineno=node.lineno, target=None,
                        decorated=node,
                        enclosing=enclosing_of.get(id(node), ""),
                    )
                    if isinstance(dec, ast.Call):
                        site.jit_call = dec
                        _apply_statics(site, dec)
                    sites.append(site)
                elif isinstance(dec, ast.Call) and _is_partial_of_jit(dec, imports):
                    site = JitSite(
                        module=module, lineno=node.lineno, target=None,
                        decorated=node, jit_call=dec,
                        enclosing=enclosing_of.get(id(node), ""),
                    )
                    _apply_statics(site, dec)
                    sites.append(site)
            continue
        if not isinstance(node, ast.Call):
            continue
        root = resolve_call_root(node.func, imports)
        if root in _JIT_NAMES and node.args:
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _apply_statics(site, node)
            sites.append(site)
        elif (
            isinstance(node.func, ast.Call)
            and _is_partial_of_jit(node.func, imports)
            and node.args
        ):
            # partial(jax.jit, ...)(fn)
            site = JitSite(
                module=module, lineno=node.lineno,
                target=_unwrap_target(node.args[0], imports, module.tree),
                jit_call=node.func,
                enclosing=enclosing_of.get(id(node), ""),
            )
            _apply_statics(site, node.func)
            sites.append(site)
    return sites
