"""Call-graph over the package's parsed modules.

Edges are REFERENCES, not just calls: in a JAX codebase functions travel as
values (``lax.scan(step, ...)``, ``lax.cond(p, do, skip, ...)``, ``vmap(f)``,
``partial(f, ...)``), so any Name/Attribute load that resolves to a known
function counts as an edge.  That over-approximates reachability, which is
the sound direction for the trace-safety pass (a function that MIGHT be
traced must be host-sync-free).

Resolution is deliberately conservative:

  - bare names resolve within the defining module (including nested and
    sibling functions),
  - ``mod.func`` attribute chains resolve through the module's import map,
  - ``self.method()`` resolves within the enclosing class,
  - anything else (duck-typed attribute calls on objects) is ignored.

Functions are keyed ``module:qualname`` (e.g. ``...ops.solve:solve_core`` or
``...solver.tpu:TPUSolver.decode``); nested functions append their name
(``solve_core.committal_block``) and lambdas get a synthetic
``<lambda@LINE>`` segment so jit-wrapped lambdas are first-class nodes.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

from karpenter_core_tpu.analysis.core import Project, SourceModule, import_map


@dataclass
class FunctionInfo:
    key: str  # "module:qualname"
    module: SourceModule
    qualname: str
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    cls: Optional[str] = None  # enclosing class name, if a method
    children: List[str] = field(default_factory=list)  # nested function keys
    refs: Set[str] = field(default_factory=set)  # resolved reference edges


class _Indexer(ast.NodeVisitor):
    def __init__(self, graph: "CallGraph", module: SourceModule) -> None:
        self.graph = graph
        self.module = module
        self.stack: List[str] = []  # qualname segments
        self.class_stack: List[str] = []
        self.parent_keys: List[str] = []

    def _register(self, name: str, node: ast.AST) -> str:
        qual = ".".join(self.stack + [name])
        key = f"{self.module.name}:{qual}"
        info = FunctionInfo(
            key=key, module=self.module, qualname=qual, node=node,
            cls=self.class_stack[-1] if self.class_stack else None,
        )
        self.graph.functions[key] = info
        self.graph._by_node[id(node)] = key
        if self.class_stack:
            # methods are reachable only as Class.name / self.name — indexing
            # them under the bare name would shadow builtins (a method called
            # ``list`` must not capture every ``list(...)`` in the module)
            self.graph._local.setdefault(
                (self.module.name, f"{self.class_stack[-1]}.{name}"), []
            ).append(key)
        else:
            self.graph._local.setdefault(
                (self.module.name, name), []
            ).append(key)
        if self.parent_keys:
            parent = self.graph.functions[self.parent_keys[-1]]
            parent.children.append(key)
        return key

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.stack.append(node.name)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.stack.pop()

    def _visit_func(self, node, name: str) -> None:
        key = self._register(name, node)
        self.stack.append(name)
        self.parent_keys.append(key)
        self.generic_visit(node)
        self.parent_keys.pop()
        self.stack.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node, node.name)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_func(node, f"<lambda@{node.lineno}>")


class CallGraph:
    def __init__(self, project: Project, modules: Optional[Iterable[SourceModule]] = None) -> None:
        self.project = project
        self.functions: Dict[str, FunctionInfo] = {}
        self._local: Dict[tuple, List[str]] = {}
        self._imports: Dict[str, Dict[str, str]] = {}
        self._by_node: Dict[int, str] = {}
        mods = list(modules) if modules is not None else project.package_modules
        for mod in mods:
            self._imports[mod.name] = import_map(mod.tree)
            _Indexer(self, mod).visit(mod.tree)
        for info in list(self.functions.values()):
            self._collect_refs(info)

    # -- resolution ------------------------------------------------------------

    def key_for_node(self, node: ast.AST) -> Optional[str]:
        """Key of a FunctionDef/Lambda ast node indexed from a project tree."""
        return self._by_node.get(id(node))

    def resolve(self, expr: ast.expr, module: SourceModule,
                enclosing: Optional[FunctionInfo] = None) -> Optional[str]:
        """Function key a Name/Attribute reference points at, or None."""
        imports = self._imports.get(module.name, {})
        if isinstance(expr, ast.Name):
            hit = self._local.get((module.name, expr.id))
            if hit:
                return hit[0]
            target = imports.get(expr.id)
            if target:
                mod_name, _, attr = target.rpartition(".")
                hit = self._local.get((mod_name, attr))
                if hit:
                    return hit[0]
            return None
        if isinstance(expr, ast.Attribute):
            # self.method() within a class
            if (
                enclosing is not None
                and enclosing.cls is not None
                and isinstance(expr.value, ast.Name)
                and expr.value.id in ("self", "cls")
            ):
                hit = self._local.get(
                    (module.name, f"{enclosing.cls}.{expr.attr}")
                )
                if hit:
                    return hit[0]
                return None
            # mod.func through the import map
            base = expr.value
            parts = [expr.attr]
            while isinstance(base, ast.Attribute):
                parts.append(base.attr)
                base = base.value
            if not isinstance(base, ast.Name):
                return None
            target = imports.get(base.id)
            if target is None:
                return None
            full = ".".join([target] + list(reversed(parts)))
            mod_name, _, attr = full.rpartition(".")
            hit = self._local.get((mod_name, attr))
            if hit:
                return hit[0]
            # class attribute access like mod.Class.method
            mod_name2, _, cls_attr = mod_name.rpartition(".")
            hit = self._local.get((mod_name2, f"{cls_attr}.{attr}"))
            if hit:
                return hit[0]
            return None
        return None

    def _collect_refs(self, info: FunctionInfo) -> None:
        """Every resolvable function reference in the body, excluding nested
        function bodies (those are separate nodes, auto-edged as children)."""
        nested = {id(self.functions[k].node) for k in info.children}

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if id(child) in nested:
                    continue
                if isinstance(child, (ast.Name, ast.Attribute)):
                    key = self.resolve(child, info.module, info)
                    if key is not None and key != info.key:
                        info.refs.add(key)
                walk(child)

        body = info.node.body
        for stmt in body if isinstance(body, list) else [body]:
            if isinstance(stmt, (ast.Name, ast.Attribute)):
                key = self.resolve(stmt, info.module, info)
                if key is not None and key != info.key:
                    info.refs.add(key)
            walk(stmt)

    # -- reachability ----------------------------------------------------------

    def reachable(self, seeds: Iterable[str]) -> Set[str]:
        """Transitive closure over reference + nested-child edges."""
        seen: Set[str] = set()
        frontier = [k for k in seeds if k in self.functions]
        while frontier:
            key = frontier.pop()
            if key in seen:
                continue
            seen.add(key)
            info = self.functions[key]
            frontier.extend(info.children)
            frontier.extend(info.refs)
        return seen


def shared_graph(project: Project) -> CallGraph:
    """One CallGraph per Project instance — passes share the build."""
    graph = getattr(project, "_shared_callgraph", None)
    if graph is None:
        graph = CallGraph(project)
        project._shared_callgraph = graph  # type: ignore[attr-defined]
    return graph
