"""metric-docs: two-way drift gate between the registered metric families
and docs/OBSERVABILITY.md (ISSUE 16).

The observability doc is the fleet-operator contract: dashboards and alert
rules are written against it, not against the source.  Metrics drift out of
it in both directions — a new family lands in code and never reaches the
doc (undocumented-metric), or a family is renamed/removed and the doc keeps
promising it (stale-doc-metric).  Both are findings; deliberate exceptions
carry baseline entries with reasons, like every other pass.

What counts as a registration (package-wide — families are registered where
they are used: tenant.py, journal.py, retry.py, watchdog.py, chaos.py,
backendprobe.py, compilecache.py, pipeline.py, the controllers — not just
metrics/registry.py):

  REGISTRY.counter("karpenter_...", ...)        # any attr base, any of the
  REGISTRY.gauge/histogram/summary(...)         # four family kinds
  Counter/Gauge/Histogram/Summary(              # direct construction, the
      NAMESPACE + "_...", ...)                  # registry.py idiom

The name operand must be a string literal or ``NAMESPACE + "_..."`` —
anything dynamic is invisible to scrapers' docs too and gets its own
finding (dynamic-metric-name).  Only ``karpenter_*`` families participate:
the ``controller_runtime_*`` compatibility names mirror controller-runtime
and are documented upstream.

Doc-side tokens are ``karpenter_[a-z0-9_]+`` words in
docs/OBSERVABILITY.md.  A token matches a family exactly, via a rendered
sample suffix (``_bucket``/``_sum``/``_count``), or as a line-wrap prefix
(token ends with ``_`` and a family starts with it).  The package-name
token ``karpenter_core_tpu...`` is ignored.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from karpenter_core_tpu.analysis.core import Finding, Project

NAME = "metric-docs"

DOC_PATH = "docs/OBSERVABILITY.md"
# metrics/registry.py NAMESPACE — resolved statically; the pass re-reads it
# from the registry module when available so a namespace rename cannot
# silently blind the gate
DEFAULT_NAMESPACE = "karpenter"

_FAMILY_KINDS = {"counter", "gauge", "histogram", "summary"}
_CTOR_NAMES = {"Counter", "Gauge", "Histogram", "Summary"}
_DOC_TOKEN = re.compile(r"karpenter_[a-z0-9_]+")
_SAMPLE_SUFFIXES = ("_bucket", "_sum", "_count")


def _namespace(project: Project) -> str:
    mod = project.get("karpenter_core_tpu.metrics.registry")
    if mod is not None:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "NAMESPACE"
                    for t in node.targets
                )
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                return node.value.value
    return DEFAULT_NAMESPACE


def _is_registration(call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr in _FAMILY_KINDS:
        return True
    return isinstance(func, ast.Name) and func.id in _CTOR_NAMES


def _literal_name(arg: ast.expr, namespace: str):
    """The family name when the operand is statically resolvable, else
    None.  Handles the two idioms: a plain string literal and the
    ``NAMESPACE + "_suffix"`` concatenation."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if (
        isinstance(arg, ast.BinOp)
        and isinstance(arg.op, ast.Add)
        and isinstance(arg.left, ast.Name)
        and arg.left.id == "NAMESPACE"
        and isinstance(arg.right, ast.Constant)
        and isinstance(arg.right.value, str)
    ):
        return namespace + arg.right.value
    return None


def collect_families(project: Project, namespace: str):
    """{family: (relpath, line)} of every karpenter_* registration in the
    package, plus findings for dynamic (unresolvable) name operands."""
    families: Dict[str, tuple] = {}
    dynamic: List[Finding] = []
    for mod in project.package_modules:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call) and _is_registration(node)):
                continue
            if not node.args:
                continue
            name = _literal_name(node.args[0], namespace)
            if name is None:
                if isinstance(node.args[0], ast.Name):
                    # a bare variable is a pass-through wrapper (the
                    # Registry.counter/... factories themselves), not a
                    # registration site
                    continue
                dynamic.append(Finding(
                    path=mod.relpath, line=node.lineno,
                    rule="dynamic-metric-name", pass_name=NAME,
                    detail="metric family name is not a string literal "
                           "(or NAMESPACE + literal) — scrapers and "
                           "docs/OBSERVABILITY.md cannot track it",
                ))
                continue
            if name.startswith(namespace + "_"):
                families.setdefault(name, (mod.relpath, node.lineno))
    return families, dynamic


def doc_tokens(text: str) -> Dict[str, int]:
    """{token: first line number} of karpenter_* words in the doc."""
    out: Dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for tok in _DOC_TOKEN.findall(line):
            out.setdefault(tok, lineno)
    return out


def run(project: Project) -> List[Finding]:
    namespace = _namespace(project)
    families, findings = collect_families(project, namespace)

    doc_file = project.root / DOC_PATH
    if not doc_file.is_file():
        # a tree that registers no families needs no doc surface (the
        # driver's synthetic fixture trees, downstream forks without
        # telemetry); one registered family makes the doc mandatory
        if families:
            findings.append(Finding(
                path=DOC_PATH, line=1, rule="missing-doc", pass_name=NAME,
                detail=f"{DOC_PATH} not found — the metric contract has no "
                       "documentation surface",
            ))
        return findings
    tokens = doc_tokens(doc_file.read_text(encoding="utf-8"))
    tokens = {
        t: ln for t, ln in tokens.items()
        if not t.startswith("karpenter_core_tpu")
    }

    def documented(family: str) -> bool:
        if family in tokens:
            return True
        for tok in tokens:
            if tok.endswith("_") and family.startswith(tok):
                return True  # line-wrapped name in the doc
            if tok.startswith(family) and tok[len(family):] in _SAMPLE_SUFFIXES:
                return True  # doc shows a rendered sample line
        return False

    for family in sorted(families):
        if not documented(family):
            path, line = families[family]
            findings.append(Finding(
                path=path, line=line, rule="undocumented-metric",
                pass_name=NAME,
                detail=f"{family} is registered but absent from {DOC_PATH}",
            ))

    def registered(tok: str) -> bool:
        if tok in families:
            return True
        if tok.endswith("_") and any(f.startswith(tok) for f in families):
            return True  # line-wrap fragment of a real family
        for family in families:
            if tok.startswith(family) and tok[len(family):] in _SAMPLE_SUFFIXES:
                return True
        return False

    for tok, lineno in sorted(tokens.items()):
        if not registered(tok):
            findings.append(Finding(
                path=DOC_PATH, line=lineno, rule="stale-doc-metric",
                pass_name=NAME,
                detail=f"{tok} is documented but no package registration "
                       "creates it",
            ))
    return findings
