"""shared-state: lockset inference for the concurrent service/fleet surface.

``lock-order`` proves the locks cannot deadlock; this pass proves the locks
actually GUARD something.  For every class (or module) in the concurrency
scope — ``service/``, ``fleet/``, ``state/``, ``solver/incremental.py``,
``utils/compilecache.py`` — that owns a ``threading.Lock`` / ``RLock`` /
``Condition``, it infers the per-method lockset held at every shared-field
access (``with self._lock:`` blocks, acquire/release pairs, interprocedural
context through same-class helper calls) and reports:

  unguarded-field      a field accessed under a lock on one path and
                       lock-free on another thread-reachable path, with at
                       least one non-init write — the classic torn-update
                       race (Eraser/RacerD lockset discipline)
  mixed-guard          every access is locked, but no single lock covers
                       them all: lock A on one path, lock B on another
  unlocked-publication a mutable container (dict/list/set) swapped in
                       lock-free while other paths mutate it under a lock —
                       readers can observe the swap mid-mutation

Soundness shape (documented, deliberate):

  - Entry points are public methods/functions plus anything registered as a
    thread target (``threading.Thread(target=...)``), an executor submit, or
    a gRPC ``*_rpc_method_handler``; a private method's incoming lockset is
    the INTERSECTION over every resolvable call site (standard lockset
    join), so one lock-free caller taints the method.  Private methods with
    no resolvable caller are skipped, and constructors (``__init__`` /
    ``__post_init__`` and helpers reachable only from them) fall out of the
    analysis naturally — that is the init-only escape hatch.
  - Companion objects: ``with entry.lock:`` where ``lock`` is the uniquely
    named lock attribute of exactly one in-scope class pins accesses like
    ``entry.recovered`` (fields declared by exactly one lock-owning class)
    to that class's lockset; a companion built by a constructor call in the
    same function (``entry = TenantEntry(...)``) is still being initialized
    and is exempt.  Companion locksets flow through same-class helper calls
    by argument-to-parameter mapping.
  - Closures and lambdas inherit their definition-point lockset: in this
    codebase inner functions are invoked synchronously downstream (solve
    hooks), so the definition site's locks are the honest approximation.
  - Duck-typed cross-class calls are invisible; a clean report is necessary,
    not sufficient.  The runtime half (karpenter_core_tpu/testing/lockcheck)
    is the dynamic witness for what this pass cannot see.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from karpenter_core_tpu.analysis.callgraph import shared_graph
from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    SourceModule,
    dotted,
    import_map,
    resolve_call_root,
)

NAME = "shared-state"

# the concurrency scope: package-relative directories and files
_SCOPE_DIRS = {"service", "fleet", "state"}
_SCOPE_FILES = {"solver/incremental.py", "utils/compilecache.py"}

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "multiprocessing.Lock",
}

# attribute calls that mutate their receiver container in place
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "appendleft", "popleft",
    "move_to_end",
}

_CONTAINER_CTORS = {
    "dict", "list", "set", "OrderedDict", "defaultdict", "deque", "Counter",
    "collections.OrderedDict", "collections.defaultdict",
    "collections.deque", "collections.Counter",
}

FuncKey = Tuple[str, str]  # (module name, top-level qualname)
Token = Tuple[str, str]  # ("self", attr) | ("mod", name) | (var, lock key)


def _in_scope(module: SourceModule, package: str) -> bool:
    name = module.name
    if not name.startswith(package + "."):
        return False
    rel = name[len(package) + 1:].split(".")
    if rel and rel[0] in _SCOPE_DIRS:
        return True
    return "/".join(rel) + ".py" in _SCOPE_FILES


def _is_container_ctor(value: ast.expr, imports: Dict[str, str]) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        root = resolve_call_root(value.func, imports)
        return root in _CONTAINER_CTORS
    return False


@dataclass
class _Unit:
    """One audited lock-owning scope: a class, or a module's globals."""

    key: str  # "module:Class" or "module:<module>"
    kind: str  # "class" | "module"
    module: SourceModule
    display: str  # short human name for details
    locks: Dict[str, str] = field(default_factory=dict)  # attr -> lock key
    declared: Set[str] = field(default_factory=set)  # shared field names
    methods: Set[str] = field(default_factory=set)  # class method names


@dataclass
class _Acc:
    kind: str  # "self" | "comp" | "glob"
    var: str  # receiver variable ("self" / companion var / global name)
    attr: str  # field name (== var for "glob")
    write: bool
    publishes: bool  # Store of a fresh container
    tokens: FrozenSet[Token]  # locally held at the access
    line: int


@dataclass
class _Edge:
    callee: FuncKey
    tokens: FrozenSet[Token]  # locally held at the call site
    argmap: Dict[str, str]  # callee param -> caller variable
    line: int


@dataclass
class _Func:
    key: FuncKey
    module: SourceModule
    node: ast.AST
    cls: Optional[str]
    qualname: str
    accesses: List[_Acc] = field(default_factory=list)
    calls: List[_Edge] = field(default_factory=list)
    ctor_vars: Set[str] = field(default_factory=set)


# -- unit discovery -----------------------------------------------------------


def _lock_ctor_kind(value: ast.expr, imports: Dict[str, str]) -> bool:
    """True when ``value`` constructs a lock (dataclass ``field(
    default_factory=threading.Lock)`` included)."""
    if not isinstance(value, ast.Call):
        return False
    root = resolve_call_root(value.func, imports)
    if root in _LOCK_CTORS:
        return True
    if root in ("field", "dataclasses.field"):
        for kw in value.keywords:
            if kw.arg == "default_factory":
                factory = None
                if isinstance(kw.value, (ast.Name, ast.Attribute)):
                    d = dotted(kw.value)
                    if d is not None:
                        head, _, rest = d.partition(".")
                        target = imports.get(head, head)
                        factory = f"{target}.{rest}" if rest else target
                if factory in _LOCK_CTORS:
                    return True
    return False


def _discover_units(
    modules: List[SourceModule],
) -> Tuple[Dict[str, _Unit], Dict[str, _Unit]]:
    """(units by key, class units by bare class name)."""
    units: Dict[str, _Unit] = {}
    by_class: Dict[str, _Unit] = {}
    for module in modules:
        imports = import_map(module.tree)
        # module unit: module-global locks + module-global containers
        mod_unit = _Unit(
            key=f"{module.name}:<module>", kind="module", module=module,
            display=module.name.rsplit(".", 1)[-1],
        )
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                name = node.targets[0].id
                if _lock_ctor_kind(node.value, imports):
                    mod_unit.locks[name] = f"{module.name}:{name}"
                elif _is_container_ctor(node.value, imports):
                    mod_unit.declared.add(name)
        if mod_unit.locks and mod_unit.declared:
            units[mod_unit.key] = mod_unit

        for cls in module.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            unit = _Unit(
                key=f"{module.name}:{cls.name}", kind="class", module=module,
                display=cls.name,
            )
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    unit.methods.add(stmt.name)
                elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    # dataclass field declarations
                    if stmt.value is not None and _lock_ctor_kind(
                        stmt.value, imports
                    ):
                        unit.locks[stmt.target.id] = (
                            f"{module.name}:{cls.name}.{stmt.target.id}"
                        )
                    else:
                        unit.declared.add(stmt.target.id)
                elif isinstance(stmt, ast.Assign) and len(
                    stmt.targets
                ) == 1 and isinstance(stmt.targets[0], ast.Name):
                    if _lock_ctor_kind(stmt.value, imports):
                        unit.locks[stmt.targets[0].id] = (
                            f"{module.name}:{cls.name}.{stmt.targets[0].id}"
                        )
            # self.X = threading.Lock() / self.X = <anything> in __init__
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        if _lock_ctor_kind(node.value, imports):
                            unit.locks[t.attr] = (
                                f"{module.name}:{cls.name}.{t.attr}"
                            )
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and fn.name in ("__init__", "__post_init__"):
                    for node in ast.walk(fn):
                        if isinstance(node, (ast.Assign, ast.AnnAssign)):
                            targets = (
                                node.targets
                                if isinstance(node, ast.Assign)
                                else [node.target]
                            )
                            for t in targets:
                                if (
                                    isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"
                                    and t.attr not in unit.locks
                                ):
                                    unit.declared.add(t.attr)
            unit.declared -= set(unit.locks)
            unit.declared -= unit.methods
            if unit.locks:
                units[unit.key] = unit
                by_class[cls.name] = unit
    return units, by_class


def _unique_map(pairs: List[Tuple[str, str]]) -> Dict[str, str]:
    """name -> value for names that map to exactly one value."""
    seen: Dict[str, Optional[str]] = {}
    for name, value in pairs:
        if name in seen and seen[name] != value:
            seen[name] = None
        else:
            seen[name] = value
    return {k: v for k, v in seen.items() if v is not None}


# -- per-function fact extraction ---------------------------------------------


class _FnWalker:
    def __init__(
        self,
        func: _Func,
        unit: Optional[_Unit],  # enclosing class unit, if any
        mod_unit: Optional[_Unit],
        imports: Dict[str, str],
        comp_locks: Dict[str, Tuple[str, str]],  # attr -> (unit key, lock key)
        unit_class_names: Set[str],
        module_funcs: Set[str],
        class_methods: Dict[str, ast.AST],
    ) -> None:
        self.func = func
        self.unit = unit
        self.mod_unit = mod_unit
        self.imports = imports
        self.comp_locks = comp_locks
        self.unit_class_names = unit_class_names
        self.module_funcs = module_funcs
        self.class_methods = class_methods
        self.held: List[Token] = []
        self._written: Set[int] = set()  # Attribute/Name ids already recorded
        self._locals = self._local_names(func.node)

    @staticmethod
    def _local_names(node: ast.AST) -> Set[str]:
        """Names bound in the function (params + assignments), used to tell
        module globals from locals.  ``global`` declarations un-bind."""
        out: Set[str] = set()
        args = getattr(node, "args", None)
        if args is not None:
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                out.add(a.arg)
        hoisted: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
            elif isinstance(sub, ast.Global):
                hoisted.update(sub.names)
        return out - hoisted

    def token_of(self, expr: ast.expr) -> Optional[Token]:
        """Held-token for a lock-typed context-manager / acquire receiver."""
        if isinstance(expr, ast.Name):
            if self.mod_unit is not None and expr.id in self.mod_unit.locks \
                    and expr.id not in self._locals:
                return ("mod", expr.id)
            return None
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            recv = expr.value.id
            if recv == "self":
                if self.unit is not None and expr.attr in self.unit.locks:
                    return ("self", expr.attr)
                return None
            if recv in self.imports:
                return None
            hit = self.comp_locks.get(expr.attr)
            if hit is not None:
                return (recv, hit[1])
        return None

    def run(self) -> None:
        body = self.func.node.body
        for stmt in body if isinstance(body, list) else [body]:
            self._walk(stmt)

    # -- access recording -----------------------------------------------------

    def _record_attr(self, node: ast.Attribute, write: bool,
                     publishes: bool = False) -> None:
        if not isinstance(node.value, ast.Name):
            return
        recv = node.value.id
        attr = node.attr
        if attr.startswith("__") and attr.endswith("__"):
            return
        tokens = frozenset(self.held)
        if recv == "self":
            if self.unit is None or attr in self.unit.locks \
                    or attr in self.unit.methods:
                return
            self.func.accesses.append(
                _Acc("self", "self", attr, write, publishes, tokens,
                     node.lineno)
            )
            self._written.add(id(node))
        else:
            if recv in self.imports or recv in self.module_funcs \
                    or recv in self.unit_class_names:
                return
            self.func.accesses.append(
                _Acc("comp", recv, attr, write, publishes, tokens,
                     node.lineno)
            )
            self._written.add(id(node))

    def _record_name(self, node: ast.Name, write: bool,
                     publishes: bool = False) -> None:
        if self.mod_unit is None or node.id not in self.mod_unit.declared:
            return
        if not write and node.id in self._locals:
            return  # shadowed by a local binding
        self.func.accesses.append(
            _Acc("glob", node.id, node.id, write, publishes,
                 frozenset(self.held), node.lineno)
        )
        self._written.add(id(node))

    def _record_target(self, target: ast.expr, publishes: bool) -> None:
        """Classify an assignment/del target as a shared-state write."""
        if isinstance(target, ast.Attribute):
            self._record_attr(target, write=True, publishes=publishes)
        elif isinstance(target, ast.Name):
            if target.id not in self._locals:  # only `global X` writes count
                self._record_name(target, write=True, publishes=publishes)
        elif isinstance(target, ast.Subscript):
            # container mutation through the receiver: d[k] = v / del d[k]
            if isinstance(target.value, ast.Attribute):
                self._record_attr(target.value, write=True)
            elif isinstance(target.value, ast.Name):
                self._record_name(target.value, write=True)
            self._walk(target.slice)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_target(elt, publishes)

    # -- the walk --------------------------------------------------------------

    def _walk(self, node: ast.AST) -> None:
        if isinstance(node, ast.With):
            taken: List[Token] = []
            for item in node.items:
                tok = self.token_of(item.context_expr)
                if tok is not None:
                    self.held.append(tok)
                    taken.append(tok)
                else:
                    self._walk(item.context_expr)
            for stmt in node.body:
                self._walk(stmt)
            for tok in reversed(taken):
                self.held.remove(tok)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            if isinstance(node, ast.Assign):
                targets = node.targets
            else:
                targets = [node.target]
            publishes = value is not None and _is_container_ctor(
                value, self.imports
            ) and not isinstance(node, ast.AugAssign)
            for t in targets:
                self._record_target(t, publishes)
            # companion-constructor escape: entry = TenantEntry(...)
            if (
                isinstance(node, ast.Assign)
                and isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in self.unit_class_names
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                self.func.ctor_vars.add(node.targets[0].id)
            if value is not None:
                self._walk(value)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._record_target(t, publishes=False)
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, ast.Attribute) and id(node) not in self._written:
            self._record_attr(node, write=False)
            # fall through: walk the receiver too? the receiver is a Name
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
                and id(node) not in self._written:
            self._record_name(node, write=False)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _call(self, node: ast.Call) -> None:
        func = node.func
        # acquire/release pairs on a known lock track like `with`
        if isinstance(func, ast.Attribute) and func.attr in (
            "acquire", "release"
        ):
            tok = self.token_of(func.value)
            if tok is not None:
                if func.attr == "acquire":
                    self.held.append(tok)
                elif tok in self.held:
                    self.held.remove(tok)
                return
        # in-place container mutation: self.d.update(...), entry.xs.append(..)
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            if isinstance(func.value, ast.Attribute):
                self._record_attr(func.value, write=True)
            elif isinstance(func.value, ast.Name):
                self._record_name(func.value, write=True)
        # propagation edges: self.helper(...) and module-level f(...)
        callee_key: Optional[FuncKey] = None
        callee_node: Optional[ast.AST] = None
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
            and self.func.cls is not None
        ):
            qual = f"{self.func.cls}.{func.attr}"
            callee_node = self.class_methods.get(qual)
            if callee_node is not None:
                callee_key = (self.func.module.name, qual)
        elif isinstance(func, ast.Name) and func.id in self.module_funcs:
            callee_node = self.class_methods.get(func.id)
            if callee_node is not None:
                callee_key = (self.func.module.name, func.id)
        if callee_key is not None and callee_node is not None:
            argmap: Dict[str, str] = {}
            args = getattr(callee_node, "args", None)
            if args is not None:
                params = [a.arg for a in args.posonlyargs + args.args]
                if params and params[0] in ("self", "cls") and isinstance(
                    func, ast.Attribute
                ):
                    params = params[1:]
                for p, a in zip(params, node.args):
                    if isinstance(a, ast.Name):
                        argmap[p] = a.id
                kwparams = {a.arg for a in args.args + args.kwonlyargs}
                for kw in node.keywords:
                    if kw.arg in kwparams and isinstance(kw.value, ast.Name):
                        argmap[kw.arg] = kw.value.id
            self.func.calls.append(
                _Edge(callee_key, frozenset(self.held), argmap, node.lineno)
            )
        for child in ast.iter_child_nodes(node):
            if child is func and callee_key is not None:
                continue
            self._walk(child)


# -- entry-point seeding ------------------------------------------------------


def _thread_seeds(project: Project) -> Set[str]:
    """Call-graph keys registered as thread targets, executor submits, or
    RPC method handlers anywhere in the package."""
    graph = shared_graph(project)
    seeds: Set[str] = set()
    imports_cache: Dict[str, Dict[str, str]] = {}
    for info in graph.functions.values():
        src = info.module.source  # textual gate: most modules register nothing
        if "Thread(" not in src and ".submit(" not in src \
                and "_rpc_method_handler" not in src:
            continue
        imports = imports_cache.setdefault(
            info.module.name, import_map(info.module.tree)
        )
        nested = {id(graph.functions[k].node) for k in info.children}

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if id(child) in nested:
                    continue
                if isinstance(child, ast.Call):
                    root = resolve_call_root(child.func, imports)
                    cands: List[ast.expr] = []
                    if root == "threading.Thread":
                        cands += [
                            kw.value for kw in child.keywords
                            if kw.arg == "target"
                        ]
                    elif isinstance(child.func, ast.Attribute) and \
                            child.func.attr == "submit" and child.args:
                        cands.append(child.args[0])
                    elif (root or "").rpartition(".")[2].endswith(
                        "_rpc_method_handler"
                    ):
                        cands += list(child.args)
                    for cand in cands:
                        key = graph.resolve(cand, info.module, info)
                        if key is not None:
                            seeds.add(key)
                walk(child)

        body = info.node.body
        for stmt in body if isinstance(body, list) else [body]:
            walk(stmt)
    return seeds


# -- the pass -----------------------------------------------------------------


def run(project: Project) -> List[Finding]:
    modules = [
        m for m in project.package_modules if _in_scope(m, project.package)
    ]
    if not modules:
        return []
    units, by_class = _discover_units(modules)
    if not units:
        return []

    # companion resolution tables: uniquely-named lock attrs and fields
    class_units = [u for u in units.values() if u.kind == "class"]
    comp_locks_flat = _unique_map(
        [(attr, key) for u in class_units for attr, key in u.locks.items()]
    )
    comp_locks = {
        attr: next(
            (u.key, key) for u in class_units if u.locks.get(attr) == key
        )
        for attr, key in comp_locks_flat.items()
    }
    field_owner = _unique_map(
        [(f, u.key) for u in class_units for f in u.declared]
    )
    unit_class_names = {u.display for u in class_units}

    # index every top-level function/method in scope, extract local facts
    funcs: Dict[FuncKey, _Func] = {}
    for module in modules:
        imports = import_map(module.tree)
        mod_unit = units.get(f"{module.name}:<module>")
        module_funcs = {
            n.name for n in module.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # flat lookup: "f" for module funcs, "Class.m" for methods
        flat: Dict[str, ast.AST] = {
            n.name: n for n in module.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for cls in module.tree.body:
            if isinstance(cls, ast.ClassDef):
                for m in cls.body:
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        flat[f"{cls.name}.{m.name}"] = m
        for qual, node in flat.items():
            cls_name = qual.split(".")[0] if "." in qual else None
            func = _Func(
                key=(module.name, qual), module=module, node=node,
                cls=cls_name, qualname=qual,
            )
            unit = units.get(f"{module.name}:{cls_name}") if cls_name else None
            _FnWalker(
                func, unit, mod_unit, imports, comp_locks, unit_class_names,
                module_funcs, flat,
            ).run()
            funcs[func.key] = func

    # seed contexts: public API + thread/executor/RPC registrations
    seeds: Set[FuncKey] = set()
    for key, func in funcs.items():
        leaf = func.qualname.split(".")[-1]
        if not leaf.startswith("_"):
            seeds.add(key)
    for gkey in _thread_seeds(project):
        mod_name, _, qual = gkey.partition(":")
        top = ".".join(qual.split(".")[:2]) if "." in qual else qual
        for cand in (qual, top, qual.split(".")[0]):
            if (mod_name, cand) in funcs:
                seeds.add((mod_name, cand))
                break

    # lockset fixpoint: context(m) = intersection over resolvable call sites
    ctx: Dict[FuncKey, Optional[FrozenSet[Token]]] = {
        k: None for k in funcs
    }
    work = deque()
    for k in seeds:
        ctx[k] = frozenset()
        work.append(k)
    while work:
        caller_key = work.popleft()
        caller = funcs[caller_key]
        base = ctx[caller_key]
        if base is None:
            continue
        caller_unit = (
            units.get(f"{caller.module.name}:{caller.cls}")
            if caller.cls else None
        )
        for edge in caller.calls:
            callee = funcs.get(edge.callee)
            if callee is None:
                continue
            incoming = base | edge.tokens
            out: Set[Token] = set()
            same_class = (
                caller.cls is not None and callee.cls == caller.cls
                and callee.module is caller.module
            )
            inv: Dict[str, List[str]] = {}
            for p, v in edge.argmap.items():
                inv.setdefault(v, []).append(p)
            for tok in incoming:
                head, tail = tok
                if head == "self":
                    if same_class:
                        out.add(tok)
                    lock_key = (
                        caller_unit.locks.get(tail) if caller_unit else None
                    )
                    if lock_key is not None:
                        for p in inv.get("self", ()):
                            out.add((p, lock_key))
                elif head == "mod":
                    if callee.cls is None and \
                            callee.module is caller.module:
                        out.add(tok)
                else:
                    for p in inv.get(head, ()):
                        out.add((p, tail))
            new = frozenset(out)
            prev = ctx[edge.callee]
            joined = new if prev is None else (prev & new)
            if joined != prev:
                ctx[edge.callee] = joined
                work.append(edge.callee)

    # collect per-(unit, field) observations
    Obs = Tuple[bool, FrozenSet[str], str, int, str, bool]
    obs: Dict[Tuple[str, str], List[Obs]] = {}
    for key, func in funcs.items():
        base = ctx[key]
        if base is None:
            continue  # unreachable / init-only: escape-analyzed away
        for acc in func.accesses:
            tokens = base | acc.tokens
            unit: Optional[_Unit] = None
            lockset: Set[str] = set()
            if acc.kind == "self":
                unit = units.get(f"{func.module.name}:{func.cls}")
                if unit is None:
                    continue
                for head, tail in tokens:
                    if head == "self" and tail in unit.locks:
                        lockset.add(unit.locks[tail])
            elif acc.kind == "comp":
                if acc.var in func.ctor_vars:
                    continue  # still under construction in this function
                owner = field_owner.get(acc.attr)
                if owner is None:
                    continue
                unit = units[owner]
                lock_keys = set(unit.locks.values())
                for head, tail in tokens:
                    if head == acc.var and tail in lock_keys:
                        lockset.add(tail)
            else:  # glob
                unit = units.get(f"{func.module.name}:<module>")
                if unit is None:
                    continue
                for head, tail in tokens:
                    if head == "mod" and tail in unit.locks:
                        lockset.add(unit.locks[tail])
            obs.setdefault((unit.key, acc.attr), []).append((
                acc.write, frozenset(lockset), func.module.relpath,
                acc.line, func.qualname, acc.publishes,
            ))

    # verdicts
    findings: List[Finding] = []
    for (unit_key, fname), observations in sorted(obs.items()):
        unit = units[unit_key]
        writes = [o for o in observations if o[0]]
        if not writes:
            continue  # read-only after init
        locked = [o for o in observations if o[1]]
        unlocked = [o for o in observations if not o[1]]
        label = f"{unit.display}.{fname}" if unit.kind == "class" else fname
        if locked and unlocked:
            lwrite, llock, lpath, lline, lqual, _ = locked[0]
            locked_mutates = any(o[0] and not o[5] for o in locked)
            if all(o[5] for o in unlocked) and locked_mutates:
                w = unlocked[0]
                findings.append(Finding(
                    w[2], w[3], "unlocked-publication",
                    f"mutable container {label!r} is published lock-free "
                    f"here while {lpath}:{lline} ({lqual}) mutates it under "
                    f"{sorted(llock)[0]!r} — readers can observe the swap "
                    "mid-mutation; publish under the same lock",
                    NAME, symbol=w[4],
                ))
            else:
                w = unlocked[0]
                findings.append(Finding(
                    w[2], w[3], "unguarded-field",
                    f"shared field {label!r} is "
                    f"{'written' if w[0] else 'read'} lock-free here but "
                    f"guarded by {sorted(llock)[0]!r} at {lpath}:{lline} "
                    f"({lqual}) — every thread-reachable access must hold "
                    "one common lock (or prove it benign in the baseline)",
                    NAME, symbol=w[4],
                ))
        elif locked:
            common = frozenset.intersection(*[o[1] for o in locked])
            if not common:
                first = locked[0]
                other = next(o for o in locked if o[1] != first[1])
                findings.append(Finding(
                    other[2], other[3], "mixed-guard",
                    f"shared field {label!r} is guarded by "
                    f"{sorted(other[1])[0]!r} here but by "
                    f"{sorted(first[1])[0]!r} at {first[2]}:{first[3]} "
                    f"({first[4]}) — no single lock covers every access",
                    NAME, symbol=other[4],
                ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
