"""lock-order: deadlock and lock-hygiene analysis for the threaded operator.

PR 2's durability work surfaced three latent concurrency bugs by accident;
this pass makes the statically-visible classes un-shippable:

  lock-order          inconsistent pairwise acquisition order: some code
                      path takes A then B while another takes B then A
                      (classic ABBA deadlock), intra- or inter-procedural
                      through resolvable calls
  self-deadlock       a non-reentrant ``threading.Lock`` acquired while the
                      same lock is already held on the call path (RLocks
                      are exempt — re-entry is their point)
  blocking-under-lock a blocking call (``.result()``, ``.wait()``,
                      ``sleep``, subprocess, socket/HTTP,
                      ``block_until_ready``, bare ``.join()``) made while
                      holding a lock, directly or through a resolvable
                      callee — every other thread needing that lock stalls
                      for the full IO/timeout
  lock-no-with        ``.acquire()`` / ``.release()`` on a known lock
                      instead of ``with`` — an exception between the two
                      leaks the lock forever

Lock identity: module-global ``X = threading.Lock()`` assignments
(``module:X``) and ``self.X = threading.Lock()`` instance attributes
(``module:Class.X``).  Call resolution mirrors the call graph's
conservative rules — ``self.method()``, module functions, imported package
functions; duck-typed attribute calls (e.g. reflector callbacks) are
invisible, so a clean report is necessary, not sufficient.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from karpenter_core_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    shared_graph,
)
from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    import_map,
    resolve_call_root,
)

NAME = "lock-order"

_BLOCKING_ROOTS = {
    "time.sleep", "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen", "socket.create_connection",
    "jax.block_until_ready", "jax.device_get",
}
_BLOCKING_BARE = {"sleep", "urlopen"}
_BLOCKING_METHODS = {
    "result", "wait", "sleep", "block_until_ready", "urlopen", "join",
    "request", "stream", "readline", "recv", "accept", "getresponse",
}


@dataclass(frozen=True)
class LockDef:
    key: str  # "module:X" or "module:Class.X"
    reentrant: bool
    path: str
    line: int


def _find_locks(project: Project) -> Dict[str, LockDef]:
    locks: Dict[str, LockDef] = {}
    for module in project.package_modules:
        imports = import_map(module.tree)

        def lock_ctor(value: ast.expr) -> Optional[bool]:
            """True/False = RLock/Lock constructor, None = not a lock."""
            if not isinstance(value, ast.Call):
                return None
            root = resolve_call_root(value.func, imports)
            if root in ("threading.RLock",):
                return True
            if root in ("threading.Lock", "threading.Semaphore",
                        "threading.BoundedSemaphore", "multiprocessing.Lock"):
                return False
            return None

        # module-level locks
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
                isinstance(node.targets[0], ast.Name)
            ):
                r = lock_ctor(node.value)
                if r is not None:
                    key = f"{module.name}:{node.targets[0].id}"
                    locks[key] = LockDef(key, r, module.relpath, node.lineno)
        # instance locks (self.X = threading.Lock() anywhere in a class)
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        r = lock_ctor(node.value)
                        if r is not None:
                            key = f"{module.name}:{cls.name}.{t.attr}"
                            locks[key] = LockDef(
                                key, r, module.relpath, node.lineno
                            )
    return locks


class _FnLockWalker:
    """Per-function facts: lock acquisitions (with held-set at that point),
    blocking calls under locks, resolvable calls under locks, raw
    acquire/release."""

    def __init__(self, info: FunctionInfo, graph: CallGraph,
                 locks: Dict[str, LockDef], imports: Dict[str, str]) -> None:
        self.info = info
        self.graph = graph
        self.locks = locks
        self.imports = imports
        self.held: List[str] = []
        # (held_tuple, acquired, line)
        self.acquisitions: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held_tuple, callee_key, line)
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []
        # (held_tuple, description, line)
        self.blocking: List[Tuple[Tuple[str, ...], str, int]] = []
        # every direct blocking call, held or not — the transitive
        # blocking-under-lock analysis consumes these (nested function
        # bodies excluded: DEFINING a sleeping closure is not sleeping)
        self.direct_blocking: List[Tuple[str, int]] = []
        self.raw: List[Tuple[str, str, int]] = []  # (lock, op, line)
        self._nested = {
            id(self.graph.functions[k].node) for k in info.children
        }

    def lock_of(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            key = f"{self.info.module.name}:{expr.id}"
            return key if key in self.locks else None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self.info.cls is not None
        ):
            key = f"{self.info.module.name}:{self.info.cls}.{expr.attr}"
            return key if key in self.locks else None
        return None

    def run(self) -> "_FnLockWalker":
        body = self.info.node.body
        for stmt in body if isinstance(body, list) else [body]:
            self._walk(stmt)
        return self

    def _walk(self, node: ast.AST) -> None:
        if id(node) in self._nested:
            return
        if isinstance(node, ast.With):
            taken: List[str] = []
            for item in node.items:
                lock = self.lock_of(item.context_expr)
                if lock is not None:
                    self.acquisitions.append((tuple(self.held), lock, node.lineno))
                    self.held.append(lock)
                    taken.append(lock)
                else:
                    self._walk(item.context_expr)
            for stmt in node.body:
                self._walk(stmt)
            for lock in reversed(taken):
                self.held.remove(lock)
            return
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _check_call(self, node: ast.Call) -> None:
        func = node.func
        # raw acquire/release on a known lock
        if isinstance(func, ast.Attribute) and func.attr in ("acquire", "release"):
            lock = self.lock_of(func.value)
            if lock is not None:
                self.raw.append((lock, func.attr, node.lineno))
                return
        held = tuple(self.held)
        desc = None
        root = resolve_call_root(func, self.imports)
        if root in _BLOCKING_ROOTS or (
            isinstance(func, ast.Name) and func.id in _BLOCKING_BARE
        ):
            desc = f"{root or func.id}()"
        elif isinstance(func, ast.Attribute) and func.attr in _BLOCKING_METHODS:
            if not (func.attr == "join" and node.args):
                # "sep".join(parts) is not a thread join; everything else
                # matching the method list counts
                desc = f".{func.attr}()"
        if desc is not None:
            self.direct_blocking.append((desc, node.lineno))
            if held:
                self.blocking.append((held, desc, node.lineno))
            return
        callee = self.graph.resolve(func, self.info.module, self.info)
        if callee is not None:
            self.calls.append((held, callee, node.lineno))


def run(project: Project) -> List[Finding]:
    graph = shared_graph(project)
    locks = _find_locks(project)
    findings: List[Finding] = []
    if not locks:
        return findings

    walkers: Dict[str, _FnLockWalker] = {}
    imports_cache: Dict[str, Dict[str, str]] = {}
    for key, info in graph.functions.items():
        imports = imports_cache.setdefault(
            info.module.name, import_map(info.module.tree)
        )
        walkers[key] = _FnLockWalker(info, graph, locks, imports).run()

    # transitive lock acquisitions per function (fixpoint over DFS w/ memo)
    acq_memo: Dict[str, Set[str]] = {}

    def acquires(key: str, stack: Set[str]) -> Set[str]:
        if key in acq_memo:
            return acq_memo[key]
        if key in stack:
            return set()
        stack = stack | {key}
        w = walkers.get(key)
        if w is None:
            return set()
        out = {lock for _, lock, _ in w.acquisitions}
        for _, callee, _ in w.calls:
            out |= acquires(callee, stack)
        acq_memo[key] = out
        return out

    # transitive blocking behavior per function: first witness
    blk_memo: Dict[str, Optional[Tuple[str, str, int]]] = {}

    def blocks(key: str, stack: Set[str]) -> Optional[Tuple[str, str, int]]:
        """(description, path, line) of a blocking call this function makes
        with NO lock of its own needed — used for callee chains."""
        if key in blk_memo:
            return blk_memo[key]
        if key in stack:
            return None
        stack = stack | {key}
        w = walkers.get(key)
        if w is None:
            return None
        info = graph.functions[key]
        if w.direct_blocking:
            desc, line = w.direct_blocking[0]
            result = (desc, info.module.relpath, line)
            blk_memo[key] = result
            return result
        for _held, callee, _line in w.calls:
            sub = blocks(callee, stack)
            if sub is not None:
                blk_memo[key] = sub
                return sub
        blk_memo[key] = None
        return None

    # -- pairwise order + direct findings -------------------------------------
    pair_witness: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def record_pair(a: str, b: str, path: str, line: int, fn: str) -> None:
        if a == b:
            return
        pair_witness.setdefault((a, b), (path, line, fn))

    for key, w in walkers.items():
        info = graph.functions[key]
        for held, lock, line in w.acquisitions:
            for h in held:
                record_pair(h, lock, info.module.relpath, line, info.qualname)
            if lock in held and not locks[lock].reentrant:
                findings.append(Finding(
                    info.module.relpath, line, "self-deadlock",
                    f"non-reentrant lock {lock!r} acquired while already "
                    "held on this path — this deadlocks; use an RLock or "
                    "restructure",
                    NAME, symbol=info.qualname,
                ))
        for held, desc, line in w.blocking:
            findings.append(Finding(
                info.module.relpath, line, "blocking-under-lock",
                f"blocking call {desc} while holding "
                f"{', '.join(repr(h) for h in held)} — every thread needing "
                "the lock stalls for the full IO/timeout; move the slow work "
                "outside the critical section",
                NAME, symbol=info.qualname,
            ))
        for lock, op, line in w.raw:
            findings.append(Finding(
                info.module.relpath, line, "lock-no-with",
                f"{lock!r}.{op}() outside a with-statement: an exception "
                "between acquire and release leaks the lock — use "
                "`with lock:`",
                NAME, symbol=info.qualname,
            ))
        # interprocedural: callee acquisitions + callee blocking under held
        for held, callee, line in w.calls:
            if not held:
                continue
            for m in sorted(acquires(callee, set())):
                for h in held:
                    record_pair(h, m, info.module.relpath, line, info.qualname)
                if m in held and not locks[m].reentrant:
                    findings.append(Finding(
                        info.module.relpath, line, "self-deadlock",
                        f"call into {graph.functions[callee].qualname!r} "
                        f"re-acquires non-reentrant lock {m!r} already held "
                        "here — this deadlocks",
                        NAME, symbol=info.qualname,
                    ))
            sub = blocks(callee, set())
            if sub is not None:
                desc, spath, sline = sub
                findings.append(Finding(
                    info.module.relpath, line, "blocking-under-lock",
                    f"call into {graph.functions[callee].qualname!r} blocks "
                    f"({desc} at {spath}:{sline}) while holding "
                    f"{', '.join(repr(h) for h in held)}",
                    NAME, symbol=info.qualname,
                ))

    # -- ABBA inversions -------------------------------------------------------
    for (a, b), (path, line, fn) in sorted(pair_witness.items()):
        if a < b and (b, a) in pair_witness:
            rpath, rline, rfn = pair_witness[(b, a)]
            findings.append(Finding(
                path, line, "lock-order",
                f"inconsistent acquisition order: {a!r} -> {b!r} here "
                f"(in {fn}) but {b!r} -> {a!r} at {rpath}:{rline} "
                f"(in {rfn}) — pick one global order",
                NAME, symbol=fn,
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
