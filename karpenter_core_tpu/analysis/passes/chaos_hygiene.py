"""chaos-hygiene: determinism and registration gates for the chaos plane.

Two properties make chaos failures replayable from their printed
``(scenario, seed)`` pair, and this pass holds both statically:

  point-duplicate     every ``chaos.point("name")`` registration name is
                      unique across the package — a second registration of
                      the same name raises at import time, but only on the
                      import path that happens to load both modules, so the
                      gate catches it before any runtime does
  point-nonliteral    ``chaos.point(...)`` must be called with a string
                      literal: the registry (docs/CHAOS.md's point catalog)
                      is audited statically, and a computed name defeats
                      both this pass and the catalog
  nondeterminism      production package modules may not import ``random``
                      or ``secrets``: every stochastic decision must flow
                      through the chaos plane (``chaos/``, the one exempt
                      subtree) or utils/retry's seedable DeterministicRNG,
                      else a fault schedule replayed from its seed diverges
                      on the first unseeded draw

tests/, tools/, and top-level scripts are exempt from ``nondeterminism``
(they are not shipped package code); nothing is exempt from the point rules.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    import_map,
    resolve_call_root,
)

NAME = "chaos-hygiene"

_FORBIDDEN_MODULES = {"random", "secrets"}
# resolved dotted roots that register a chaos point
_POINT_CALLS = {
    "karpenter_core_tpu.chaos.point",
    "karpenter_core_tpu.chaos.plane.point",
}


def _is_chaos_module(module, project: Project) -> bool:
    parts = module.name.split(".")
    return len(parts) > 1 and parts[1] == "chaos"


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    registrations: Dict[str, List[Tuple[str, int]]] = {}

    for module in project.package_modules:
        imports = import_map(module.tree)
        chaos_exempt = _is_chaos_module(module, project)

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)) and not chaos_exempt:
                if isinstance(node, ast.Import):
                    roots = [alias.name.split(".")[0] for alias in node.names]
                else:
                    roots = [(node.module or "").split(".")[0]]
                for root in roots:
                    if root in _FORBIDDEN_MODULES:
                        findings.append(Finding(
                            module.relpath, node.lineno, "nondeterminism",
                            f"production module imports {root!r}; stochastic "
                            "decisions must flow through chaos/ or "
                            "utils/retry.DeterministicRNG so fault schedules "
                            "replay from their seed",
                            NAME,
                        ))
            if isinstance(node, ast.Call):
                root = resolve_call_root(node.func, imports)
                if root not in _POINT_CALLS:
                    continue
                if (
                    len(node.args) != 1
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)
                ):
                    findings.append(Finding(
                        module.relpath, node.lineno, "point-nonliteral",
                        "chaos.point() must take a single string literal — "
                        "the point catalog is audited statically",
                        NAME,
                    ))
                    continue
                point_name = node.args[0].value
                registrations.setdefault(point_name, []).append(
                    (module.relpath, node.lineno)
                )

    for point_name, sites in sorted(registrations.items()):
        if len(sites) > 1:
            rendered = ", ".join(f"{p}:{line}" for p, line in sites)
            for path, line in sites:
                findings.append(Finding(
                    path, line, "point-duplicate",
                    f"chaos point {point_name!r} registered {len(sites)} "
                    f"times ({rendered}); register once and import the "
                    "Point object everywhere else",
                    NAME,
                ))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
