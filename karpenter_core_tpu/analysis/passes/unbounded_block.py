"""unbounded-block: device blocking reachable outside a watchdog scope.

The watchdog (utils/watchdog.py) is only hang-proof if every blocking
device interaction actually routes through it — one raw
``jax.device_get`` / ``block_until_ready`` / deferred-handle ``.result()``
on the solve path reintroduces exactly the unbounded wait the r02–r05
hangs demonstrated.  This rule extends the PR-4 blocking-call machinery
(analysis/passes/lock_order's blocking set) to the device-path subtrees:

  unbounded-block   a blocking device call (``jax.device_get``,
                    ``jax.block_until_ready``, method spellings
                    ``.device_get()``/``.block_until_ready()``, or
                    ``.result()``) in a device-path module, outside any
                    MonitoredDispatch scope — i.e. not lexically inside a
                    ``watchdog.run(...)`` / ``MonitoredDispatch(...).run(...)``
                    call and not in utils/watchdog.py itself.

Passing the blocking callable INTO the watchdog
(``watchdog.run(site, jax.device_get, tree)``) produces no Call node and
is automatically clean — the preferred integration shape.  Deliberate
residual sites (host-thread futures like the compilecache upload overlap,
deferred-handle retirement that settles through the monitored session)
carry baseline entries with reasons; the rule exists so NEW unbounded
blocking can't land unexplained.
"""

from __future__ import annotations

import ast
from typing import List

from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    import_map,
    resolve_call_root,
)

NAME = "unbounded-block"

# package-relative dotted prefixes of the device-path subtrees the rule
# watches (controllers/ and models/ never hold device handles directly; the
# watchdog module itself is the monitored scope)
_WATCHED_PREFIXES = (
    "ops.", "solver.", "parallel.", "service.",
)
_WATCHED_MODULES = ("utils.pipeline", "utils.compilecache")
_EXEMPT_MODULES = ("utils.watchdog",)

# dotted roots / method names that block on device values
_BLOCKING_ROOTS = {"jax.device_get", "jax.block_until_ready"}
_BLOCKING_METHODS = {"device_get", "block_until_ready", "result"}

# resolved dotted roots that ARE the monitored scope: any blocking call
# lexically inside one of these call expressions is watchdog-bounded
_MONITORED_CALLS = {
    "karpenter_core_tpu.utils.watchdog.run",
    "watchdog.run",
    "watchdog_mod.run",
}


def _relname(module) -> str:
    """Module name relative to the package root (``utils.pipeline``)."""
    parts = module.name.split(".")
    return ".".join(parts[1:]) if len(parts) > 1 else module.name


def _watched(module) -> bool:
    rel = _relname(module)
    if rel in _EXEMPT_MODULES:
        return False
    return rel in _WATCHED_MODULES or any(
        rel.startswith(p) for p in _WATCHED_PREFIXES
    )


class _Walker(ast.NodeVisitor):
    """Collect blocking calls with their enclosing symbol, tracking how many
    monitored-scope call expressions enclose the current node."""

    def __init__(self, imports) -> None:
        self.imports = imports
        self.stack: List[str] = []
        self.monitored_depth = 0
        self.hits: List[tuple] = []  # (line, desc, symbol)

    def _symbol(self) -> str:
        return ".".join(self.stack)

    def _scoped(self, node, name: str) -> None:
        self.stack.append(name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scoped(node, node.name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scoped(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scoped(node, node.name)

    def _is_monitored(self, node: ast.Call) -> bool:
        root = resolve_call_root(node.func, self.imports)
        if root in _MONITORED_CALLS:
            return True
        # MonitoredDispatch(...).run(...) style, NARROWLY: the receiver must
        # be a MonitoredDispatch construction or a name/attr that literally
        # says "watchdog" — a generic ``something_dispatch.run(...)`` must
        # NOT silently exempt the blocking calls nested inside it
        if isinstance(node.func, ast.Attribute) and node.func.attr == "run":
            recv = node.func.value
            if isinstance(recv, ast.Call):
                recv_root = resolve_call_root(recv.func, self.imports) or ""
                if recv_root.rsplit(".", 1)[-1] == "MonitoredDispatch":
                    return True
            if isinstance(recv, ast.Name) and "watchdog" in recv.id.lower():
                return True
            if isinstance(recv, ast.Attribute) and (
                "watchdog" in recv.attr.lower()
            ):
                return True
        return False

    def visit_Call(self, node: ast.Call) -> None:
        monitored = self._is_monitored(node)
        if monitored:
            self.monitored_depth += 1
        if self.monitored_depth == 0:
            root = resolve_call_root(node.func, self.imports)
            desc = None
            if root in _BLOCKING_ROOTS:
                desc = root
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_METHODS
            ):
                desc = f".{node.func.attr}()"
            if desc is not None:
                self.hits.append((node.lineno, desc, self._symbol()))
        self.generic_visit(node)
        if monitored:
            self.monitored_depth -= 1


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for module in project.package_modules:
        if not _watched(module):
            continue
        walker = _Walker(import_map(module.tree))
        walker.visit(module.tree)
        for line, desc, symbol in walker.hits:
            findings.append(Finding(
                module.relpath, line, NAME,
                f"blocking device call {desc} outside a MonitoredDispatch "
                "scope — a quiet device hangs it forever; route it through "
                "utils/watchdog.run (or baseline it with the reason it is "
                "bounded)",
                NAME,
                symbol=symbol,
            ))
    return findings
