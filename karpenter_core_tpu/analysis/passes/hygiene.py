"""hygiene: the generic lint rules, ported from the bespoke tools/lint.py
walker onto the framework (tools/lint.py is now a thin CLI over this pass).

Rules carried over unchanged:

  unused-import       imported name never referenced (module ``__init__.py``
                      re-export files and ``__all__`` names are exempt;
                      identifier-boundary matches in string constants count
                      as uses, the documented forward-reference
                      over-approximation)
  bare-except         ``except:`` with no exception class
  mutable-default     list/dict/set literals as parameter defaults
  f-string-no-field   f-string without any substitution
  tabs / trailing-ws  formatting gate
  long-line           > 120 characters

New with the framework:

  assert-in-package   ``assert`` statements in shipped package code —
                      ``python -O`` strips them, so they are not error
                      handling; ``karpenter_core_tpu/testing/`` (the test
                      harness) and tests/ are exempt
  wallclock           ``time.time()`` / ``datetime.now()`` /
                      ``datetime.utcnow()`` in the reconcile world
                      (controllers/, state/, operator/, solver/, kubeapi/,
                      soak/, policy/): TTL logic and soak timelines must go
                      through utils/clock.Clock so suites advance time
                      deterministically (and soak verdicts replay from
                      their seed)
  per-pod-loop        Python ``for`` loops (and comprehensions) iterating a
                      pod collection inside the encode hot path
                      (models/columnar.py, models/snapshot.py): the
                      delta-native ingest (docs/KERNEL_PERF.md "Layer 6")
                      columnarized the per-pod work into interned fast keys
                      and numpy batch ops, and a new O(pods)-body loop would
                      silently regress the million-pod tick budget.  The
                      deliberate residual loops (the bulk-add driver whose
                      body is O(1) dict work, the cold classify_pods batch
                      path) carry baseline entries with reasons — the rule
                      exists so NEW ones can't land unexplained.
"""

from __future__ import annotations

import ast
import re
from typing import List

from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    SourceModule,
    import_map,
    resolve_call_root,
)

NAME = "hygiene"
MAX_LINE = 120

# package subtrees where wall-clock reads must route through utils/clock.py
# (soak/ is in: its probes and traces live on the FakeClock timeline, and a
# stray wall read would silently break verdict seed-replay; policy/ is in:
# objective decisions and counter-proposals run inside reconciles and soak
# ticks, so a wall read there breaks the same replay guarantees; service/ is
# in: the tenant plane's TTL/lease/breaker/bucket policy must step on
# FakeClock for the multi-tenant suites, and service/journal.py's record
# timestamps ride the injected Clock so durable-session recovery tests run
# on FakeClock — latency MEASUREMENT uses time.perf_counter, which stays
# allowed)
_CLOCKED_DIRS = (
    "controllers", "state", "operator", "solver", "kubeapi", "soak", "policy",
    "service",
)
_WALLCLOCK_CALLS = {
    "time.time", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

# encode-hot-path modules the per-pod-loop rule watches (package-relative
# dotted suffixes) and the identifier names that mark an iterable as a pod
# collection when they appear anywhere inside a loop's iterated expression
_PER_POD_LOOP_MODULES = ("models.columnar", "models.snapshot")
_POD_COLLECTION_NAMES = {
    "pods", "all_pods", "bound_pods", "tpu_pods", "host_pods", "pending_pods",
}


def _iter_mentions_pods(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in _POD_COLLECTION_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _POD_COLLECTION_NAMES:
            return True
    return False


class _PodLoopWalker(ast.NodeVisitor):
    """Collect (line, symbol) of loops/comprehensions over pod collections,
    tracking the enclosing function/class qualname so baseline entries can
    match on ``symbol`` instead of a rot-prone line number."""

    def __init__(self) -> None:
        self.stack: List[str] = []
        self.hits: List[tuple] = []

    def _symbol(self) -> str:
        return ".".join(self.stack)

    def _scoped(self, node, name: str) -> None:
        self.stack.append(name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scoped(node, node.name)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scoped(node, node.name)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scoped(node, node.name)

    def visit_For(self, node: ast.For) -> None:
        if _iter_mentions_pods(node.iter):
            self.hits.append((node.lineno, self._symbol()))
        self.generic_visit(node)

    def _check_comp(self, node) -> None:
        for gen in node.generators:
            if _iter_mentions_pods(gen.iter):
                self.hits.append((node.lineno, self._symbol()))
                break
        self.generic_visit(node)

    visit_ListComp = _check_comp
    visit_SetComp = _check_comp
    visit_DictComp = _check_comp
    visit_GeneratorExp = _check_comp


class _Walker(ast.NodeVisitor):
    def __init__(self) -> None:
        self.imports: dict = {}  # name -> (line, module)
        self.used: set = set()
        self.findings: List[tuple] = []  # (line, rule, detail)
        self.dunder_all: set = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports[name] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports[name] = (node.lineno, f"{node.module}.{alias.name}")

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, ast.Name) and target.id == "__all__":
                for element in ast.walk(node.value):
                    if isinstance(element, ast.Constant) and isinstance(
                        element.value, str
                    ):
                        self.dunder_all.add(element.value)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self.findings.append(
                (node.lineno, "bare-except", "use `except Exception:`")
            )
        self.generic_visit(node)

    def _check_defaults(self, node) -> None:
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(
                    (default.lineno, "mutable-default", "use None + in-body init")
                )

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.findings.append(
                (node.lineno, "f-string-no-field", "drop the f prefix")
            )
        # visit interpolated expressions — including those inside dynamic
        # format specs — but never a spec's JoinedStr itself (a field-less
        # inner JoinedStr would false-positive the no-field check)
        def visit_fields(joined: ast.JoinedStr) -> None:
            for value in joined.values:
                if isinstance(value, ast.FormattedValue):
                    self.visit(value.value)
                    if isinstance(value.format_spec, ast.JoinedStr):
                        visit_fields(value.format_spec)

        visit_fields(node)


def check_module(module: SourceModule, project: Project) -> List[Finding]:
    out: List[Finding] = []

    def finding(line: int, rule: str, detail: str) -> None:
        out.append(Finding(module.relpath, line, rule, detail, NAME))

    for i, line in enumerate(module.lines, 1):
        if "\t" in line:
            finding(i, "tabs", "use spaces")
        if line != line.rstrip():
            finding(i, "trailing-ws", "trailing whitespace")
        if len(line) > MAX_LINE:
            finding(i, "long-line", f"{len(line)} > {MAX_LINE}")

    walker = _Walker()
    walker.visit(module.tree)
    # string-annotation references ("Optional[Clock]") count as uses — the
    # documented over-approximation from the original lint.py
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for name in walker.imports:
                if re.search(rf"\b{re.escape(name)}\b", node.value):
                    walker.used.add(name)
    if module.path.name != "__init__.py":
        for name, (lineno, target) in sorted(walker.imports.items()):
            if name not in walker.used and name not in walker.dunder_all:
                finding(lineno, "unused-import", f"{target} as {name}")
    for lineno, rule, detail in walker.findings:
        finding(lineno, rule, detail)

    # -- assert-in-package -----------------------------------------------------
    in_shipped_package = module.in_package and not module.name.startswith(
        f"{project.package}.testing"
    )
    if in_shipped_package:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assert):
                finding(
                    node.lineno, "assert-in-package",
                    "assert in shipped package code disappears under "
                    "`python -O`; raise an exception instead",
                )

    # -- per-pod-loop ----------------------------------------------------------
    if module.in_package and any(
        module.name.endswith(f".{suffix}") for suffix in _PER_POD_LOOP_MODULES
    ):
        pod_walker = _PodLoopWalker()
        pod_walker.visit(module.tree)
        for lineno, symbol in pod_walker.hits:
            out.append(Finding(
                module.relpath, lineno, "per-pod-loop",
                "Python loop over a pod collection in the encode hot path — "
                "columnarize it (interned fast keys / numpy batch ops) or "
                "baseline it with a reason (docs/KERNEL_PERF.md Layer 6)",
                NAME, symbol=symbol,
            ))

    # -- wallclock -------------------------------------------------------------
    parts = module.name.split(".")
    if module.in_package and len(parts) > 1 and parts[1] in _CLOCKED_DIRS:
        imports = import_map(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                root = resolve_call_root(node.func, imports)
                if root in _WALLCLOCK_CALLS:
                    finding(
                        node.lineno, "wallclock",
                        f"{root}() in reconcile-world code defeats FakeClock "
                        "determinism; take a utils/clock.Clock and call "
                        ".now()",
                    )
    return out


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for module in project.all_modules:
        findings.extend(check_module(module, project))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
