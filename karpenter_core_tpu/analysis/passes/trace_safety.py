"""trace-safety: host syncs and trace breakers inside jit-reachable code.

Computes the set of functions reachable from every ``jax.jit`` entry point
in the package (ops/solve.py, ops/masks.py, ops/consolidate.py,
parallel/mesh.py, the compile-cache lambdas — discovery is package-wide, the
named modules are just where the entries live today) and flags, inside that
set:

  host-sync      ``.item()`` / ``.tolist()`` / ``np.asarray`` / ``np.array``
                 / ``jax.device_get`` / ``block_until_ready`` / ``float()``
                 / ``int()`` / ``bool()`` applied to a traced value (a
                 ``jnp``/``jax`` call result, directly or through a local
                 assignment)
  trace-branch   Python ``if``/``while`` whose test is a traced value
                 (where detectable by the same taint rule)
  host-effect    wall-clock (``time.*``), ``print``, and logging calls —
                 these run at TRACE time, not run time, so they lie about
                 when they execute and differ under retrace
  try-in-trace   ``try/except`` around traced ops — tracer errors escape
                 the except at trace time while runtime errors never reach
                 it, so the handler is dead either way

One accidental host sync in this set turns the 1.27 s warm solve back into
a 30 s retrace-and-block (PR 3); nothing at runtime catches it because the
result is still *correct*.

The taint rule is deliberately shallow (calls rooted at jnp/jax aliases,
propagated through simple ``name = <tainted>`` assignments in the same
function): parameters of transitively-reached helpers may be static python
values (e.g. the mask width in ops/masks.py), so "any parameter is traced"
would drown the signal in false positives.  Real-but-undetectable syncs are
the retrace-budget fixture's job to catch at runtime.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from karpenter_core_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    shared_graph,
)
from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    import_map,
    resolve_call_root,
)
from karpenter_core_tpu.analysis.jitsites import (
    find_jit_sites,
    find_shard_map_sites,
)

NAME = "trace-safety"

_SYNC_ATTRS = {"item", "tolist"}
_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "jax.device_get", "jax.block_until_ready",
}
_CAST_BUILTINS = {"float", "int", "bool"}
_TIME_ROOT = "time"
_LOG_ROOTS = {"logging"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical"}
_TRACED_ROOTS = ("jax.numpy", "jax.lax", "jax.nn", "jax.random", "jax.scipy", "jax")
# jax.* calls that do NOT produce/consume runtime-traced values
_TRACED_EXEMPT = {
    "jax.numpy.dtype", "jax.tree_util.tree_map", "jax.tree_util.tree_leaves",
}


def _norm_numpy(root: str) -> str:
    return "numpy" + root[2:] if root == "np" or root.startswith("np.") else root


def _is_traced_call(node: ast.expr, imports: Dict[str, str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    root = resolve_call_root(node.func, imports)
    if root is None or root in _TRACED_EXEMPT:
        return False
    return any(root == r or root.startswith(r + ".") for r in _TRACED_ROOTS)


class _FnChecker:
    def __init__(self, info: FunctionInfo, imports: Dict[str, str]) -> None:
        self.info = info
        self.imports = imports
        self.findings: List[Finding] = []
        self.tainted: Set[str] = set()
        self._nested = set()

    def _finding(self, node: ast.AST, rule: str, detail: str) -> None:
        self.findings.append(
            Finding(
                self.info.module.relpath, getattr(node, "lineno", 0), rule,
                detail, NAME, symbol=self.info.qualname,
            )
        )

    def _expr_tainted(self, node: ast.expr) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if _is_traced_call(sub, self.imports):
                return True
        return False

    def run(self, nested_nodes) -> List[Finding]:
        self._nested = {id(n) for n in nested_nodes}
        body = self.info.node.body
        for stmt in body if isinstance(body, list) else [body]:
            self._walk(stmt)
        return self.findings

    def _walk(self, node: ast.AST) -> None:
        if id(node) in self._nested:
            return
        if isinstance(node, ast.Assign):
            self._walk(node.value)
            if self._expr_tainted(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.tainted.add(target.id)
            return
        if isinstance(node, ast.Try):
            self._finding(
                node, "try-in-trace",
                "try/except around traced ops: tracer errors raise at trace "
                "time and runtime errors never reach python — hoist the "
                "fallible host work out of the jitted path",
            )
        if isinstance(node, (ast.If, ast.While)):
            if self._expr_tainted(node.test):
                self._finding(
                    node, "trace-branch",
                    "python branch on a traced value forces a host sync at "
                    "trace time (ConcretizationTypeError or silent retrace); "
                    "use jnp.where / lax.cond",
                )
        if isinstance(node, ast.Call):
            self._check_call(node)
        for child in ast.iter_child_nodes(node):
            self._walk(child)

    def _check_call(self, node: ast.Call) -> None:
        func = node.func
        root = resolve_call_root(func, self.imports)
        root = _norm_numpy(root) if root else root
        # .item() / .tolist() on anything in a traced context
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_ATTRS:
            self._finding(
                node, "host-sync",
                f".{func.attr}() blocks on the device inside jit-reachable "
                "code — return the array and convert outside the kernel",
            )
            return
        if isinstance(func, ast.Attribute) and func.attr == "block_until_ready":
            self._finding(
                node, "host-sync",
                "block_until_ready inside jit-reachable code synchronizes "
                "the device mid-trace",
            )
            return
        if root in _SYNC_CALLS:
            if root in ("numpy.asarray", "numpy.array"):
                # np.asarray of host/static data at trace time constant-folds
                # and is fine; only a traced operand makes it a device fetch
                if not any(self._expr_tainted(a) for a in node.args):
                    return
            self._finding(
                node, "host-sync",
                f"{root}(...) fetches a traced value to host inside "
                "jit-reachable code",
            )
            return
        if (
            isinstance(func, ast.Name)
            and func.id in _CAST_BUILTINS
            and node.args
            and self._expr_tainted(node.args[0])
        ):
            self._finding(
                node, "host-sync",
                f"{func.id}() on a traced value blocks on the device "
                "(ConcretizationTypeError under jit); keep it an array",
            )
            return
        if root is not None:
            if root == _TIME_ROOT or root.startswith(_TIME_ROOT + "."):
                self._finding(
                    node, "host-effect",
                    f"{root}() runs at trace time, not solve time — timing "
                    "inside the kernel measures tracing, and the value "
                    "freezes into the compiled program",
                )
                return
            head = root.split(".")[0]
            if head in _LOG_ROOTS or (
                isinstance(func, ast.Attribute)
                and func.attr in _LOG_METHODS
                and isinstance(func.value, ast.Name)
                and func.value.id in ("log", "logger", "logging")
            ):
                self._finding(
                    node, "host-effect",
                    "logging inside jit-reachable code fires once at trace "
                    "time (use jax.debug.print for runtime values)",
                )
                return
        if isinstance(func, ast.Name) and func.id == "print":
            self._finding(
                node, "host-effect",
                "print inside jit-reachable code fires once at trace time "
                "(use jax.debug.print)",
            )


def jit_entry_keys(project: Project, graph: CallGraph) -> List[str]:
    """Function keys of every jax.jit AND shard_map target in the package —
    a shard_map body is traced device code exactly like a jitted function
    (host syncs inside it hang the per-device program), so sharded bodies
    seed the same reachability set."""
    keys: List[str] = []
    for module in project.package_modules:
        for site in find_jit_sites(module) + find_shard_map_sites(module):
            if site.decorated is not None:
                key = graph.key_for_node(site.decorated)
            elif site.target is not None:
                if isinstance(site.target, ast.Lambda):
                    key = graph.key_for_node(site.target)
                else:
                    key = graph.resolve(site.target, module)
            else:
                key = None
            if key is not None:
                keys.append(key)
    return keys


def run(project: Project) -> List[Finding]:
    graph = shared_graph(project)
    entries = jit_entry_keys(project, graph)
    reachable = graph.reachable(entries)
    findings: List[Finding] = []
    imports_cache: Dict[str, Dict[str, str]] = {}
    for key in sorted(reachable):
        info = graph.functions[key]
        imports = imports_cache.setdefault(
            info.module.name, import_map(info.module.tree)
        )
        nested = [graph.functions[k].node for k in info.children]
        findings.extend(_FnChecker(info, imports).run(nested))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
