"""instrumented: every controller ``reconcile`` opens a tracing span.

Port of tools/check_instrumented.py onto the framework (that script is now
a thin CLI over this pass).  A controller class — one carrying a literal
string ``name`` attribute, the operator registration contract — must have
its ``reconcile`` either decorated with ``@tracing.traced(...)`` /
``@traced(...)`` or contain a ``with tracing.span(...)`` / ``with
span(...)`` block, so new controllers cannot ship invisible to
/debug/traces and the stage histograms.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from karpenter_core_tpu.analysis.core import Finding, Project, SourceModule

NAME = "instrumented"


def _is_span_call(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    func = call.func
    if isinstance(func, ast.Name):
        return func.id == "span"
    if isinstance(func, ast.Attribute):
        return func.attr == "span"
    return False


def _is_traced_decorator(node: ast.expr) -> bool:
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Name):
        return node.id == "traced"
    if isinstance(node, ast.Attribute):
        return node.attr == "traced"
    return False


def _opens_span(fn: ast.FunctionDef) -> bool:
    if any(_is_traced_decorator(d) for d in fn.decorator_list):
        return True
    for node in ast.walk(fn):
        if isinstance(node, ast.With):
            if any(_is_span_call(item.context_expr) for item in node.items):
                return True
    return False


def _controller_classes(tree: ast.Module) -> Iterator[Tuple[ast.ClassDef, str]]:
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "name"
                    for t in stmt.targets
                )
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)
            ):
                yield node, stmt.value.value
                break


def check_module(module: SourceModule) -> List[Finding]:
    findings: List[Finding] = []
    for cls, controller_name in _controller_classes(module.tree):
        for stmt in cls.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == "reconcile":
                if not _opens_span(stmt):
                    findings.append(Finding(
                        module.relpath, stmt.lineno, "uninstrumented-reconcile",
                        f"controller {controller_name!r} ({cls.name}."
                        "reconcile) opens no tracing span — decorate with "
                        "@tracing.traced(...) or wrap the body in "
                        "`with tracing.span(...)`",
                        NAME, symbol=f"{cls.name}.reconcile",
                    ))
    return findings


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    prefix = f"{project.package}.controllers"
    for module in project.package_modules:
        if module.name == prefix or module.name.startswith(prefix + "."):
            findings.extend(check_module(module))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
