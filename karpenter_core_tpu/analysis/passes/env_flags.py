"""env-flags: every KC_* environment flag is registered and documented.

The service grew ~50 ``KC_*`` tuning flags (KC_PIPELINE, KC_WATCHDOG,
KC_COALESCE_WINDOW, KC_BUCKET_QUANTIZE, KC_FLEET_CHECKPOINT_KEEP, ...) with
no central inventory: a flag you cannot find is a flag you cannot audit,
and a dead registry row is documentation that lies.  This pass closes the
loop in both directions against the central registry
(``karpenter_core_tpu/utils/flags.py`` ``FLAGS`` table) and the docs table
(``docs/FLAGS.md``):

  unregistered-read  a ``KC_*`` read (``os.environ.get`` / ``os.environ[...]``
                     / ``os.getenv`` / ``"KC_X" in os.environ`` / a literal
                     flag name passed to an env-helper like ``_env_f``) whose
                     flag is missing from the registry
  dead-entry         a registry row no package code reads
  undocumented-flag  a registry row missing from the docs/FLAGS.md table

Scope is the package only: bench/tools/tests harness flags (KC_BENCH_*,
KC_PERF_GATE_STRICT, ...) are out of band and stay out of the registry.
Helper indirection is inferred, not hard-coded: any package function whose
parameter flows into an environ read is an env-helper, and literal first
arguments at its call sites count as reads of that flag.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    SourceModule,
    dotted,
    import_map,
)

NAME = "env-flags"

_FLAG_RE = re.compile(r"\bKC_[A-Z0-9_]+\b")

_REGISTRY_REL = "utils/flags.py"
_DOCS_REL = "docs/FLAGS.md"


def _norm(expr: ast.expr, imports: Dict[str, str]) -> Optional[str]:
    """Dotted name with the import map applied: ``environ.get`` ->
    ``os.environ.get`` under ``from os import environ``."""
    name = dotted(expr)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    target = imports.get(head, head)
    return f"{target}.{rest}" if rest else target


def _flag_of(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str) and \
            _FLAG_RE.fullmatch(expr.value):
        return expr.value
    return None


def _param_of(expr: ast.expr, params: Set[str]) -> Optional[str]:
    if isinstance(expr, ast.Name) and expr.id in params:
        return expr.id
    return None


def _env_read_arg(node: ast.AST, imports: Dict[str, str]) -> Optional[ast.expr]:
    """The flag-name expression of an environment read, or None."""
    if isinstance(node, ast.Call):
        root = _norm(node.func, imports)
        if root in ("os.getenv", "os.environ.get", "os.environ.setdefault",
                    "os.environ.pop") and node.args:
            return node.args[0]
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if _norm(node.value, imports) == "os.environ":
            return node.slice
    if isinstance(node, ast.Compare) and len(node.ops) == 1 and isinstance(
        node.ops[0], (ast.In, ast.NotIn)
    ):
        if node.comparators and _norm(
            node.comparators[0], imports
        ) == "os.environ":
            return node.left
    return None


def _load_registry(
    project: Project,
) -> Tuple[Optional[SourceModule], Dict[str, int]]:
    """(registry module, flag -> line in flags.py)."""
    module = project.get(f"{project.package}.utils.flags")
    if module is None:
        return None, {}
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target: Optional[ast.expr] = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        else:
            continue
        if isinstance(target, ast.Name) and target.id == "FLAGS" and \
                isinstance(node.value, ast.Dict):
            out: Dict[str, int] = {}
            for key in node.value.keys:
                flag = _flag_of(key) if key is not None else None
                if flag is not None:
                    out[flag] = key.lineno
            return module, out
    return module, {}


def run(project: Project) -> List[Finding]:
    registry_mod, registry = _load_registry(project)

    # first sweep: find env-helper functions (a param flows into a read)
    helpers: Set[str] = set()  # bare function names, matched by leaf
    for module in project.package_modules:
        imports = import_map(module.tree)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {
                a.arg for a in fn.args.posonlyargs + fn.args.args
                + fn.args.kwonlyargs
            }
            for node in ast.walk(fn):
                arg = _env_read_arg(node, imports)
                if arg is not None and _param_of(arg, params) is not None:
                    helpers.add(fn.name)
                    break

    # second sweep: every flag read site in the package
    reads: List[Tuple[str, SourceModule, int]] = []  # (flag, module, line)
    for module in project.package_modules:
        imports = import_map(module.tree)
        for node in ast.walk(module.tree):
            arg = _env_read_arg(node, imports)
            if arg is not None:
                flag = _flag_of(arg)
                if flag is not None:
                    reads.append((flag, module, node.lineno))
                continue
            if isinstance(node, ast.Call) and node.args:
                leaf = None
                if isinstance(node.func, ast.Name):
                    leaf = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    leaf = node.func.attr
                if leaf in helpers:
                    flag = _flag_of(node.args[0])
                    if flag is not None:
                        reads.append((flag, module, node.lineno))

    findings: List[Finding] = []
    registry_path = f"{project.package}/{_REGISTRY_REL}"
    if registry_mod is not None:
        registry_path = registry_mod.relpath

    for flag, module, line in reads:
        if flag not in registry:
            findings.append(Finding(
                module.relpath, line, "unregistered-read",
                f"{flag} is read here but missing from the FLAGS registry "
                f"({registry_path}) — register it with a one-line "
                "description so the flag surface stays auditable",
                NAME,
            ))

    read_flags = {flag for flag, _, _ in reads}
    docs_path = project.root / _DOCS_REL
    try:
        documented = set(_FLAG_RE.findall(docs_path.read_text()))
    except OSError:
        documented = set()
    for flag, line in sorted(registry.items()):
        if flag not in read_flags:
            findings.append(Finding(
                registry_path, line, "dead-entry",
                f"registry entry {flag} is never read by package code — "
                "delete the row (or the dead flag plumbing it described)",
                NAME,
            ))
        if flag not in documented:
            findings.append(Finding(
                registry_path, line, "undocumented-flag",
                f"registry entry {flag} is missing from the {_DOCS_REL} "
                "table — every registered flag needs a documented default "
                "and effect",
                NAME,
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
