"""retrace-budget: static jit-declaration consistency with the compile cache.

The compile cache (utils/compilecache.py) keys executables on a tuple of
static config fields; ``jax.jit`` keys its own cache on static_argnums /
static_argnames.  The two drift independently, and each direction of drift
is a distinct production bug:

  static-args       a compile-cache key field that is a parameter of a
                    jitted solve entry but is NOT declared static there —
                    jit would trace it as an array (wrong program) or
                    silently key a retrace per value
  cache-key-drift   a static_argname of a solve jit site that is also a
                    ``solve_callable`` parameter but does NOT appear in the
                    compile-cache key — two configs would collide on one
                    memoized executable (silent wrong reuse)
  non-literal-static  static_argnums/static_argnames computed at runtime:
                    unauditable, and typo'd names fail only when the site
                    first runs
  unknown-static    a declared static name that is not a parameter of the
                    jitted target (typo — jax raises only on first call)
  unhashable-static a dict/list/set literal passed for a static parameter
                    at a call site of a known jitted wrapper, or a static
                    parameter whose default is a mutable literal — jit
                    raises ``unhashable type`` at solve time
  uncached-jit      ``jax.jit(...)`` constructed inside a function that is
                    not memoized (lru_cache): every call builds a fresh
                    wrapper with an empty jit cache, so every call retraces
                    (the bug class ops.consolidate._lane_sweep_fn's
                    docstring describes)
  donated-read      a buffer passed to a donating dispatch site is read
                    again afterwards in the same function — the classic
                    use-after-donate footgun of the pipelined solve loop
                    (docs/KERNEL_PERF.md "Layer 7"): the executable consumed
                    the device memory, so the read either raises
                    "buffer deleted" or (with a live host view) silently
                    degrades donation to a realloc.  Donating sites are
                    (a) calls whose callee name ends in ``_donated``
                    (ops.solve.repair_free_donated / scatter_repair_window
                    _donated — by convention their FIRST positional
                    argument is donated) and (b) ``run_prepared`` /
                    ``run_solve`` calls with a ``warm_carry=`` keyword (the
                    carry is donated whenever the pipeline is armed).
                    Branch-aware: donation inside one arm of an if/else
                    taints only that arm and the code after the branch.

The runtime half of this pass lives in tests/conftest.py: a fixture counts
actual XLA compilations per tier-1 test against the checked-in manifest
``karpenter_core_tpu/analysis/retrace_budget.json``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from karpenter_core_tpu.analysis.callgraph import shared_graph
from karpenter_core_tpu.analysis.core import (
    Finding,
    Project,
    SourceModule,
    import_map,
    resolve_call_root,
)
from karpenter_core_tpu.analysis.jitsites import (
    JitSite,
    _PARTIAL_NAMES,
    find_jit_sites,
    find_shard_map_sites,
)

NAME = "retrace-budget"

_MEMO_DECORATORS = {
    "functools.lru_cache", "lru_cache", "functools.cache", "cache",
}
_MUTABLE_LITERALS = (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp,
                     ast.SetComp)


def _params(fn: ast.AST) -> List[str]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return []
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)]


def _param_defaults(fn: ast.AST) -> Dict[str, ast.expr]:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return {}
    a = fn.args
    out: Dict[str, ast.expr] = {}
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        out[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        if d is not None:
            out[p.arg] = d
    return out


def _is_memoized(fn, imports: Dict[str, str]) -> bool:
    """The function carries a memoizing decorator (lru_cache/cache) — its
    per-call jit/shard_map constructions build once per distinct key."""
    if fn is None or not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        droot = resolve_call_root(
            dec.func if isinstance(dec, ast.Call) else dec, imports
        )
        if droot in _MEMO_DECORATORS:
            return True
    return False


def _mesh_derives_from_params(mesh_expr: ast.expr, fn: ast.AST) -> bool:
    """True when a shard_map's mesh expression references (or chases, through
    one local single-assignment, to an expression referencing) at least one
    parameter of the enclosing memoized builder — the mesh topology is then
    part of the memo key by construction (``mesh = mesh_for(mesh_axes)``).
    A mesh pulled from module scope or a closure is NOT keyed: two
    topologies would silently share one cached executable."""
    params = set(_params(fn))
    if not params:
        return False

    def names_of(expr: ast.expr):
        return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}

    if names_of(mesh_expr) & params:
        return True
    if isinstance(mesh_expr, ast.Name):
        hits = [
            node.value
            for node in ast.walk(fn)
            if isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == mesh_expr.id
        ]
        if len(hits) == 1 and names_of(hits[0]) & params:
            return True
    return False


# donating dispatch sites for the donated-read rule: callees whose
# ``warm_carry=`` keyword argument is donated when the pipeline is armed
# (utils.compilecache.run_solve / solver.tpu.TPUSolver.run_prepared), plus
# the ``*_donated`` helper convention (first positional argument donated —
# ops/solve.py repair_free_donated / scatter_repair_window_donated)
_DONATING_CALLEES = {"run_prepared", "run_solve"}


def _call_donations(node: ast.Call) -> List[str]:
    """Plain names this call donates, per the donating-site conventions."""
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    out: List[str] = []
    if name.endswith("_donated"):
        if node.args and isinstance(node.args[0], ast.Name):
            out.append(node.args[0].id)
    elif name in _DONATING_CALLEES:
        for kw in node.keywords:
            if kw.arg == "warm_carry" and isinstance(kw.value, ast.Name):
                out.append(kw.value.id)
    return out


def _donated_read_findings(module: SourceModule) -> List[Finding]:
    """The donated-read rule (module docstring): an intra-procedural,
    branch-aware walk flagging reads of a name after the dispatch that
    donated its buffer.  Rebinding the name clears the taint (``carry =
    repair_free_donated(carry, ...)`` is the intended idiom — the name then
    holds the dispatch's OUTPUT, not the consumed input).  Aliased callees
    (``fn = x_donated; fn(...)``) are not chased — the rule is a tripwire
    for the direct spellings the solve path uses, not an escape-proof
    dataflow analysis."""
    findings: List[Finding] = []

    def flag(name: str, read_line: int, donate_line: int, qual: str) -> None:
        findings.append(Finding(
            module.relpath, read_line, "donated-read",
            f"{name!r} is read after being donated to the dispatch at line "
            f"{donate_line} — the executable consumed its device buffer; "
            "use the dispatch's returned value, or keep an undonated "
            "reference taken before the call",
            NAME, symbol=qual,
        ))

    def check_reads(node: ast.AST, donated: Dict[str, int], qual: str) -> None:
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in donated
            ):
                flag(sub.id, sub.lineno, donated[sub.id], qual)
                donated.pop(sub.id, None)  # one finding per donation

    def register(node: ast.AST, donated: Dict[str, int]) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                for nm in _call_donations(sub):
                    donated[nm] = sub.lineno

    def clear_binds(targets, donated: Dict[str, int]) -> None:
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    donated.pop(sub.id, None)

    def scan(stmts, donated: Dict[str, int], qual: str) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs get their own fresh scope below
            if isinstance(stmt, ast.If):
                check_reads(stmt.test, donated, qual)
                register(stmt.test, donated)
                body_d, else_d = dict(donated), dict(donated)
                scan(stmt.body, body_d, qual)
                scan(stmt.orelse, else_d, qual)
                donated.clear()
                donated.update(body_d)
                donated.update(else_d)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                head = stmt.iter if hasattr(stmt, "iter") else stmt.test
                check_reads(head, donated, qual)
                register(head, donated)
                if hasattr(stmt, "target"):
                    clear_binds([stmt.target], donated)
                body_d = dict(donated)
                scan(stmt.body, body_d, qual)
                scan(stmt.orelse, body_d, qual)
                donated.update(body_d)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    check_reads(item.context_expr, donated, qual)
                    register(item.context_expr, donated)
                    if item.optional_vars is not None:
                        clear_binds([item.optional_vars], donated)
                scan(stmt.body, donated, qual)
                continue
            if isinstance(stmt, ast.Try):
                scan(stmt.body, donated, qual)
                for handler in stmt.handlers:
                    h_d = dict(donated)
                    scan(handler.body, h_d, qual)
                    donated.update(h_d)
                scan(stmt.orelse, donated, qual)
                scan(stmt.finalbody, donated, qual)
                continue
            # simple statement: reads first (the donating call's own
            # argument is not yet tainted), then new donations, then
            # rebound targets drop their taint
            check_reads(stmt, donated, qual)
            register(stmt, donated)
            if isinstance(stmt, ast.Assign):
                clear_binds(stmt.targets, donated)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                clear_binds([stmt.target], donated)

    def walk_fns(node: ast.AST, qual: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = ".".join(qual + [child.name])
                scan(child.body, {}, q)
                walk_fns(child, qual + [child.name])
            elif isinstance(child, ast.ClassDef):
                walk_fns(child, qual + [child.name])
            else:
                walk_fns(child, qual)

    walk_fns(module.tree, [])
    return findings


def _fn_index(module: SourceModule) -> Dict[str, ast.AST]:
    """qualname -> FunctionDef for the module (dotted by nesting)."""
    out: Dict[str, ast.AST] = {}

    def walk(node: ast.AST, qual: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[".".join(qual + [child.name])] = child
                walk(child, qual + [child.name])
            elif isinstance(child, ast.ClassDef):
                walk(child, qual + [child.name])
            else:
                walk(child, qual)

    walk(module.tree, [])
    return out


def _static_key_names(expr: ast.expr) -> Set[str]:
    """Parameter names the cache key STATICALLY keys on.  Names inside
    helper calls other than ``tuple(...)`` are excluded: ``_leaf_sig(cls)``
    keys on shapes/dtypes — those stay runtime (traced) arguments, only the
    directly-embedded config values are static."""
    out: Set[str] = set()

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "tuple":
                for a in node.args:
                    walk(a)
            return
        if isinstance(node, ast.Name):
            out.add(node.id)
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return out


def cache_key_fields(project: Project) -> Tuple[Set[str], Optional[SourceModule]]:
    """Parameter names of ``solve_callable`` referenced by its ``key = (...)``
    expression — the compile-cache's static config axis.  Empty when the
    project has no compilecache module (temp trees in tests)."""
    mod = project.get(f"{project.package}.utils.compilecache")
    if mod is None:
        return set(), None
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "solve_callable"
        ):
            params = set(_params(node))
            for stmt in ast.walk(node):
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "key"
                ):
                    used = _static_key_names(stmt.value)
                    return used & params, mod
    return set(), mod


def solve_callable_params(project: Project) -> Set[str]:
    mod = project.get(f"{project.package}.utils.compilecache")
    if mod is None:
        return set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            node.name == "solve_callable"
        ):
            return set(_params(node))
    return set()


def _target_binds(site: JitSite, imports: Dict[str, str]) -> Tuple[bool, Set[str]]:
    """(went_through_partial, kwarg names bound by partial wrappers) for the
    site's ORIGINAL (pre-unwrap) target expression."""
    if site.jit_call is None or not getattr(site.jit_call, "args", None):
        return False, set()
    expr = site.jit_call.args[0]
    via_partial = False
    bound: Set[str] = set()
    while isinstance(expr, ast.Call):
        root = resolve_call_root(expr.func, imports)
        if root in _PARTIAL_NAMES and expr.args:
            via_partial = True
            bound |= {kw.arg for kw in expr.keywords if kw.arg}
            expr = expr.args[0]
            continue
        if root in ("jax.vmap", "vmap") and expr.args:
            expr = expr.args[0]
            continue
        break
    return via_partial, bound


def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    graph = shared_graph(project)
    key_fields, cc_mod = cache_key_fields(project)
    sc_params = solve_callable_params(project)
    solve_core_key = f"{project.package}.ops.solve:solve_core"

    # wrapper name -> (static names, target params) for unhashable checks
    wrappers: Dict[str, Tuple[Tuple[str, ...], List[str]]] = {}

    for module in project.package_modules:
        imports = import_map(module.tree)
        fn_index = _fn_index(module)
        # use-after-donate tripwire for the pipelined loop's donating
        # dispatch sites (docs/KERNEL_PERF.md "Layer 7")
        findings.extend(_donated_read_findings(module))
        sites = find_jit_sites(module)
        for site in sites:
            statics = tuple(site.static_argnames or ())
            # resolve the jitted function node
            if site.decorated is not None:
                target_node: Optional[ast.AST] = site.decorated
                target_key = graph.key_for_node(site.decorated)
            elif site.target is not None:
                if isinstance(site.target, ast.Lambda):
                    target_node = site.target
                    target_key = graph.key_for_node(site.target)
                else:
                    target_key = graph.resolve(site.target, module)
                    target_node = (
                        graph.functions[target_key].node
                        if target_key in graph.functions
                        else None
                    )
            else:
                target_node, target_key = None, None

            if site.non_literal_statics:
                findings.append(Finding(
                    module.relpath, site.lineno, "non-literal-static",
                    "static_argnums/static_argnames must be literal "
                    "constants so the declaration is auditable",
                    NAME, symbol=site.enclosing,
                ))

            target_params = _params(target_node) if target_node is not None else []
            if target_node is not None and statics:
                for name in statics:
                    if name not in target_params:
                        findings.append(Finding(
                            module.relpath, site.lineno, "unknown-static",
                            f"static_argnames entry {name!r} is not a "
                            "parameter of the jitted function",
                            NAME, symbol=site.enclosing,
                        ))
                defaults = _param_defaults(target_node)
                for name in statics:
                    d = defaults.get(name)
                    if d is not None and isinstance(d, _MUTABLE_LITERALS):
                        findings.append(Finding(
                            module.relpath, site.lineno, "unhashable-static",
                            f"static parameter {name!r} defaults to a "
                            "mutable literal; jit raises 'unhashable type' "
                            "when the default is used",
                            NAME, symbol=site.enclosing,
                        ))

            # consistency with the compile-cache key, both directions
            if key_fields and target_node is not None:
                relevant = target_key == solve_core_key or bool(
                    set(statics) & key_fields
                )
                if relevant:
                    via_partial, bound = _target_binds(site, imports)
                    static_nums = site.static_argnums or ()
                    by_pos = {
                        target_params[i]
                        for i in static_nums
                        if 0 <= i < len(target_params)
                    }
                    declared = set(statics) | by_pos | bound
                    defaults = _param_defaults(target_node)
                    for f in sorted(key_fields & set(target_params)):
                        if f in declared:
                            continue
                        if via_partial and f in defaults:
                            # partial-built wrapper: the field stays at its
                            # python default, which is a trace-time constant
                            continue
                        findings.append(Finding(
                            module.relpath, site.lineno, "static-args",
                            f"compile-cache key field {f!r} is a runtime "
                            "argument at this jit site — declare it in "
                            "static_argnames or bind it via partial",
                            NAME, symbol=site.enclosing,
                        ))
                    if cc_mod is not None:
                        for name in sorted(set(statics) & sc_params - key_fields):
                            findings.append(Finding(
                                module.relpath, site.lineno, "cache-key-drift",
                                f"static arg {name!r} is a solve_callable "
                                "parameter but absent from the compile-cache "
                                "key tuple — distinct configs would share "
                                "one memoized executable "
                                f"({cc_mod.relpath})",
                                NAME, symbol=site.enclosing,
                            ))

            # per-call jit construction
            if site.enclosing:
                if not _is_memoized(fn_index.get(site.enclosing), imports):
                    findings.append(Finding(
                        module.relpath, site.lineno, "uncached-jit",
                        "jax.jit constructed per call inside "
                        f"{site.enclosing!r}: each call gets a fresh wrapper "
                        "with an empty jit cache and retraces — memoize the "
                        "builder (functools.lru_cache) or hoist to module "
                        "scope",
                        NAME, symbol=site.enclosing,
                    ))

            # record module-level wrapper assignments for call-site checks
            if statics and site.decorated is None and not site.enclosing:
                parent = _assign_name_for(module.tree, site)
                if parent:
                    wrappers[f"{module.name}.{parent}"] = (statics, target_params)
            elif statics and site.decorated is not None:
                qual = getattr(site.decorated, "name", "")
                if qual and not site.enclosing:
                    wrappers[f"{module.name}.{qual}"] = (statics, target_params)

        # shard_map sites (the mesh dispatch layer, docs/KERNEL_PERF.md
        # "Layer 5"): same per-call-construction hazard as jax.jit, plus the
        # mesh-keying rule — a memoized builder whose shard_map captures a
        # mesh that does NOT derive from the builder's parameters silently
        # shares one executable across mesh topologies (the sharded twin of
        # cache-key-drift)
        for site in find_shard_map_sites(module):
            if site.enclosing:
                enclosing_fn = fn_index.get(site.enclosing)
                memoized = _is_memoized(enclosing_fn, imports)
                if not memoized:
                    findings.append(Finding(
                        module.relpath, site.lineno, "uncached-jit",
                        "shard_map constructed per call inside "
                        f"{site.enclosing!r}: each call builds a fresh "
                        "sharded wrapper with an empty jit cache and "
                        "retraces — memoize the builder "
                        "(functools.lru_cache) or hoist to module scope",
                        NAME, symbol=site.enclosing,
                    ))
                else:
                    mesh_expr = site.kwargs.get("mesh")
                    if mesh_expr is not None and not _mesh_derives_from_params(
                        mesh_expr, enclosing_fn
                    ):
                        findings.append(Finding(
                            module.relpath, site.lineno, "unkeyed-mesh-static",
                            "shard_map mesh inside memoized builder "
                            f"{site.enclosing!r} does not derive from the "
                            "builder's parameters — distinct mesh topologies "
                            "would share one cached executable; thread the "
                            "topology through the cache key (e.g. "
                            "mesh_for(mesh_axes))",
                            NAME, symbol=site.enclosing,
                        ))

    # unhashable literals at call sites of known jitted wrappers
    for module in project.package_modules:
        imports = import_map(module.tree)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            root = resolve_call_root(node.func, imports)
            if root is None:
                continue
            hit = wrappers.get(root)
            if hit is None and "." not in root:
                hit = wrappers.get(f"{module.name}.{root}")
            if hit is None:
                continue
            statics, target_params = hit
            for kw in node.keywords:
                if kw.arg in statics and isinstance(kw.value, _MUTABLE_LITERALS):
                    findings.append(Finding(
                        module.relpath, node.lineno, "unhashable-static",
                        f"static arg {kw.arg!r} receives a mutable literal "
                        f"({type(kw.value).__name__.lower()}); jit raises "
                        "'unhashable type' — pass a tuple / frozen value",
                        NAME,
                    ))
            for i, arg in enumerate(node.args):
                if i < len(target_params) and target_params[i] in statics and (
                    isinstance(arg, _MUTABLE_LITERALS)
                ):
                    findings.append(Finding(
                        module.relpath, node.lineno, "unhashable-static",
                        f"static arg {target_params[i]!r} receives a mutable "
                        "literal; jit raises 'unhashable type' — pass a "
                        "tuple / frozen value",
                        NAME,
                    ))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _assign_name_for(tree: ast.Module, site: JitSite) -> Optional[str]:
    """Name a module-level ``X = jax.jit(...)`` / ``X = partial(jax.jit,
    ...)(...)`` assignment binds, when the site is such a value."""
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        if not isinstance(node.targets[0], ast.Name):
            continue
        for sub in ast.walk(node.value):
            if sub is site.jit_call or (
                getattr(sub, "lineno", None) == site.lineno
                and isinstance(sub, ast.Call)
                and sub is node.value
            ):
                return node.targets[0].id
    return None
