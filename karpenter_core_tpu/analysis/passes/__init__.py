"""Pass registry for tools/kcanalyze.py.

Each pass module exposes ``NAME`` (the kebab-case pass id used in findings
and baseline entries) and ``run(project) -> list[Finding]``.  To add a pass,
write the module, append it here, and document it in docs/ANALYSIS.md.
"""

from karpenter_core_tpu.analysis.passes import (
    chaos_hygiene,
    env_flags,
    hygiene,
    instrumented,
    lock_order,
    metric_docs,
    retrace_budget,
    shared_state,
    trace_safety,
    unbounded_block,
)

ALL_PASSES = [
    trace_safety, retrace_budget, lock_order, hygiene, instrumented,
    chaos_hygiene, unbounded_block, metric_docs, shared_state, env_flags,
]

__all__ = ["ALL_PASSES"]
